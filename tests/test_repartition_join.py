"""Repartition (shuffled) hash equi-join over the 8-device CPU mesh.

The q65 shape: store_sales (sharded fact) ⋈ item (sharded build side — NOT
replicated) on item_sk, aggregating sales by item category.  Differential
oracle: pandas merge+groupby on the same host data.
"""

import numpy as np
import pandas as pd
import pytest
import jax
import jax.numpy as jnp

import spark_rapids_jni_tpu as sr
from spark_rapids_jni_tpu.parallel import make_mesh
from spark_rapids_jni_tpu.parallel.repartition_join import (
    JoinAggSpec, repartition_join_agg, repartition_join_agg_auto)

N_DEV = 8


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(N_DEV, "data")


def _case(n_fact=4096, n_item=512, n_cat=7, null_keys=False, seed=0):
    rng = np.random.default_rng(seed)
    item_sk = rng.permutation(np.arange(10_000, dtype=np.int64))[:n_item]
    item_cat = rng.integers(0, n_cat, n_item).astype(np.int32)
    # fact keys: mostly joinable, some missing from item (no match)
    fact_sk = np.where(rng.random(n_fact) < 0.85,
                       item_sk[rng.integers(0, n_item, n_fact)],
                       rng.integers(20_000, 30_000, n_fact)).astype(np.int64)
    fact_qty = rng.integers(1, 100, n_fact).astype(np.int64)
    fact_valid = np.ones((n_fact, 2), dtype=bool)
    item_valid = np.ones((n_item, 2), dtype=bool)
    if null_keys:
        fact_valid[:, 0] = rng.random(n_fact) < 0.9
        item_valid[:, 0] = rng.random(n_item) < 0.95
    return item_sk, item_cat, fact_sk, fact_qty, fact_valid, item_valid


def _oracle(item_sk, item_cat, fact_sk, fact_qty, fact_valid, item_valid,
            n_cat):
    df_i = pd.DataFrame({"sk": item_sk, "cat": item_cat})[item_valid[:, 0]]
    df_f = pd.DataFrame({"sk": fact_sk, "qty": fact_qty})[fact_valid[:, 0]]
    j = df_f.merge(df_i, on="sk", how="inner")
    g = j.groupby("cat")["qty"].agg(["sum", "count"])
    sums = np.zeros(n_cat, np.int64)
    cnts = np.zeros(n_cat, np.int64)
    sums[g.index.to_numpy()] = g["sum"].to_numpy()
    cnts[g.index.to_numpy()] = g["count"].to_numpy()
    return sums, cnts


def _run(mesh, item_sk, item_cat, fact_sk, fact_qty, fact_valid, item_valid,
         n_cat, fact_capacity=None, build_capacity=None):
    n_fact, n_item = fact_sk.shape[0], item_sk.shape[0]
    spec = JoinAggSpec(
        fact_schema=(sr.int64, sr.int64),
        build_schema=(sr.int64, sr.int32),
        fact_key_idx=0, build_key_idx=0, build_group_idx=1,
        fact_value_idx=1, num_groups=n_cat,
        fact_capacity=fact_capacity or (2 * n_fact // N_DEV // N_DEV + 64),
        build_capacity=build_capacity or (2 * n_item // N_DEV // N_DEV + 64))
    sums, cnts, dropped = repartition_join_agg(
        mesh, spec,
        (jnp.asarray(fact_sk), jnp.asarray(fact_qty)),
        jnp.asarray(fact_valid),
        (jnp.asarray(item_sk), jnp.asarray(item_cat)),
        jnp.asarray(item_valid))
    return (np.asarray(sums), np.asarray(cnts), int(np.asarray(dropped)))


def test_q65_shape_matches_pandas(mesh):
    case = _case()
    sums, cnts, dropped = _run(mesh, *case, n_cat=7)
    want_s, want_c = _oracle(*case, n_cat=7)
    assert dropped == 0
    np.testing.assert_array_equal(sums, want_s)
    np.testing.assert_array_equal(cnts, want_c)


def test_null_keys_never_match(mesh):
    case = _case(null_keys=True, seed=3)
    sums, cnts, dropped = _run(mesh, *case, n_cat=7)
    want_s, want_c = _oracle(*case, n_cat=7)
    assert dropped == 0
    np.testing.assert_array_equal(sums, want_s)
    np.testing.assert_array_equal(cnts, want_c)


def test_capacity_overflow_is_reported(mesh):
    case = _case(seed=5)
    _, _, dropped = _run(mesh, *case, n_cat=7, fact_capacity=2)
    assert dropped > 0  # two-phase sizing: caller must retry with headroom


def test_skewed_keys_all_land(mesh):
    # heavy skew: 60% of fact rows share ONE key — they all hash to one
    # partition, so capacity must cover the skew (reported if not)
    rng = np.random.default_rng(9)
    n_fact, n_item, n_cat = 2048, 64, 5
    item_sk = np.arange(100, 100 + n_item, dtype=np.int64)
    item_cat = rng.integers(0, n_cat, n_item).astype(np.int32)
    hot = item_sk[7]
    fact_sk = np.where(rng.random(n_fact) < 0.6, hot,
                       item_sk[rng.integers(0, n_item, n_fact)]).astype(np.int64)
    fact_qty = rng.integers(1, 10, n_fact).astype(np.int64)
    fv = np.ones((n_fact, 2), bool)
    iv = np.ones((n_item, 2), bool)
    sums, cnts, dropped = _run(mesh, item_sk, item_cat, fact_sk, fact_qty,
                               fv, iv, n_cat,
                               fact_capacity=2 * n_fact // N_DEV)
    want_s, want_c = _oracle(item_sk, item_cat, fact_sk, fact_qty, fv, iv,
                             n_cat)
    assert dropped == 0
    np.testing.assert_array_equal(sums, want_s)
    np.testing.assert_array_equal(cnts, want_c)


def test_duplicate_build_keys_expand_matches(mesh):
    # cudf inner_join semantics: each fact row joins EVERY matching build
    # row.  Build side: ~3 rows per key on average, different categories.
    rng = np.random.default_rng(11)
    n_fact, n_item, n_cat = 2048, 384, 6
    base_keys = np.arange(500, 500 + n_item // 3, dtype=np.int64)
    item_sk = rng.choice(base_keys, n_item).astype(np.int64)
    item_cat = rng.integers(0, n_cat, n_item).astype(np.int32)
    fact_sk = np.where(rng.random(n_fact) < 0.8,
                       base_keys[rng.integers(0, base_keys.shape[0], n_fact)],
                       rng.integers(90_000, 99_000, n_fact)).astype(np.int64)
    fact_qty = rng.integers(1, 50, n_fact).astype(np.int64)
    fv = np.ones((n_fact, 2), bool)
    iv = np.ones((n_item, 2), bool)
    sums, cnts, dropped = _run(mesh, item_sk, item_cat, fact_sk, fact_qty,
                               fv, iv, n_cat,
                               fact_capacity=n_fact, build_capacity=n_item)
    want_s, want_c = _oracle(item_sk, item_cat, fact_sk, fact_qty, fv, iv,
                             n_cat)
    assert dropped == 0
    np.testing.assert_array_equal(sums, want_s)
    np.testing.assert_array_equal(cnts, want_c)


def test_duplicate_keys_with_nulls(mesh):
    rng = np.random.default_rng(13)
    n_fact, n_item, n_cat = 1024, 256, 5
    base = np.arange(10, 110, dtype=np.int64)
    item_sk = rng.choice(base, n_item).astype(np.int64)
    item_cat = rng.integers(0, n_cat, n_item).astype(np.int32)
    fact_sk = base[rng.integers(0, base.shape[0], n_fact)].astype(np.int64)
    fact_qty = rng.integers(1, 9, n_fact).astype(np.int64)
    fv = np.ones((n_fact, 2), bool)
    iv = np.ones((n_item, 2), bool)
    fv[:, 0] = rng.random(n_fact) < 0.9
    iv[:, 0] = rng.random(n_item) < 0.9
    sums, cnts, dropped = _run(mesh, item_sk, item_cat, fact_sk, fact_qty,
                               fv, iv, n_cat,
                               fact_capacity=n_fact, build_capacity=n_item)
    want_s, want_c = _oracle(item_sk, item_cat, fact_sk, fact_qty, fv, iv,
                             n_cat)
    assert dropped == 0
    np.testing.assert_array_equal(sums, want_s)
    np.testing.assert_array_equal(cnts, want_c)


def test_auto_capacity_never_drops(mesh):
    # the shape that overflowed with fact_capacity=2 sizes itself now —
    # including under the skew that concentrates 60% on one partition
    rng = np.random.default_rng(9)
    n_fact, n_item, n_cat = 2048, 64, 5
    item_sk = np.arange(100, 100 + n_item, dtype=np.int64)
    item_cat = rng.integers(0, n_cat, n_item).astype(np.int32)
    fact_sk = np.where(rng.random(n_fact) < 0.6, item_sk[7],
                       item_sk[rng.integers(0, n_item, n_fact)]).astype(np.int64)
    fact_qty = rng.integers(1, 10, n_fact).astype(np.int64)
    fv = np.ones((n_fact, 2), bool)
    iv = np.ones((n_item, 2), bool)
    sums, cnts, dropped = repartition_join_agg_auto(
        mesh, (sr.int64, sr.int64), (sr.int64, sr.int32),
        0, 0, 1, 1, n_cat,
        (jnp.asarray(fact_sk), jnp.asarray(fact_qty)), jnp.asarray(fv),
        (jnp.asarray(item_sk), jnp.asarray(item_cat)), jnp.asarray(iv))
    want_s, want_c = _oracle(item_sk, item_cat, fact_sk, fact_qty, fv, iv,
                             n_cat)
    assert int(np.asarray(dropped)) == 0
    np.testing.assert_array_equal(np.asarray(sums), want_s)
    np.testing.assert_array_equal(np.asarray(cnts), want_c)


def test_max_value_key_still_joins(mesh):
    # a legitimate PK equal to iinfo(int64).max must not be conflated with
    # the dead-slot sentinel
    n_fact, n_cat = 256, 3
    item_sk = np.asarray([5, 9, np.iinfo(np.int64).max], np.int64)
    item_cat = np.asarray([0, 1, 2], np.int32)
    fact_sk = np.asarray([5, np.iinfo(np.int64).max] * (n_fact // 2),
                         np.int64)
    fact_qty = np.ones(n_fact, np.int64)
    fv = np.ones((n_fact, 2), bool)
    iv = np.ones((3, 2), bool)
    # pad item side to a multiple of the mesh (8): extra rows are nulls
    pad = 8 - 3
    item_sk = np.concatenate([item_sk, np.zeros(pad, np.int64)])
    item_cat = np.concatenate([item_cat, np.zeros(pad, np.int32)])
    iv = np.concatenate([iv, np.zeros((pad, 2), bool)])
    sums, cnts, dropped = _run(mesh, item_sk, item_cat, fact_sk, fact_qty,
                               fv, iv, n_cat, fact_capacity=n_fact,
                               build_capacity=8)
    assert dropped == 0
    assert cnts.tolist() == [n_fact // 2, 0, n_fact // 2]
    assert sums.tolist() == [n_fact // 2, 0, n_fact // 2]


def test_multikey_composite_auto_matches_pandas(mesh):
    # 2-column key packed into one composite lane: shuffle routing and the
    # local dense probe share it, so both sides of a tuple key still land
    # on one chip and results match a host pandas multi-key merge exactly
    rng = np.random.default_rng(17)
    n_fact, n_item, n_cat = 2048, 256, 6
    item_a = rng.integers(100, 160, n_item).astype(np.int64)
    item_b = rng.integers(0, 12, n_item).astype(np.int32)
    item_cat = rng.integers(0, n_cat, n_item).astype(np.int32)
    fact_a = np.where(rng.random(n_fact) < 0.8,
                      rng.integers(100, 160, n_fact),
                      rng.integers(900, 950, n_fact)).astype(np.int64)
    fact_b = rng.integers(0, 12, n_fact).astype(np.int32)
    fact_qty = rng.integers(1, 30, n_fact).astype(np.int64)
    fv = np.ones((n_fact, 3), bool)
    iv = np.ones((n_item, 3), bool)
    fv[:, 0] = rng.random(n_fact) < 0.9      # null keys never match
    iv[:, 1] = rng.random(n_item) < 0.9
    sums, cnts, dropped = repartition_join_agg_auto(
        mesh, (sr.int64, sr.int32, sr.int64), (sr.int64, sr.int32, sr.int32),
        [0, 1], [0, 1], 2, 2, n_cat,
        (jnp.asarray(fact_a), jnp.asarray(fact_b), jnp.asarray(fact_qty)),
        jnp.asarray(fv),
        (jnp.asarray(item_a), jnp.asarray(item_b), jnp.asarray(item_cat)),
        jnp.asarray(iv))
    df_i = pd.DataFrame({"a": item_a, "b": item_b,
                         "cat": item_cat})[iv[:, 0] & iv[:, 1]]
    df_f = pd.DataFrame({"a": fact_a, "b": fact_b,
                         "qty": fact_qty})[fv[:, 0] & fv[:, 1]]
    g = df_f.merge(df_i, on=["a", "b"]).groupby("cat")["qty"].agg(
        ["sum", "count"])
    want_s = np.zeros(n_cat, np.int64)
    want_c = np.zeros(n_cat, np.int64)
    want_s[g.index.to_numpy()] = g["sum"].to_numpy()
    want_c[g.index.to_numpy()] = g["count"].to_numpy()
    assert int(np.asarray(dropped)) == 0
    np.testing.assert_array_equal(np.asarray(sums), want_s)
    np.testing.assert_array_equal(np.asarray(cnts), want_c)


def test_multikey_overflow_raises(mesh):
    # 63-bit window overflow: the shard path has no fingerprint fallback
    big = np.asarray([-2**61, 2**61], np.int64)
    fd = (jnp.asarray(big), jnp.asarray(big), jnp.asarray([1, 1], np.int64))
    bd = (jnp.asarray(big), jnp.asarray(big),
          jnp.asarray([0, 1], np.int32))
    v = jnp.ones((2, 3), bool)
    with pytest.raises(ValueError, match="63"):
        repartition_join_agg_auto(
            mesh, (sr.int64, sr.int64, sr.int64),
            (sr.int64, sr.int64, sr.int32),
            [0, 1], [0, 1], 2, 2, 2, fd, v, bd, v)
