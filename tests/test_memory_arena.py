"""HBM arena subsystem tests: slab pool, budgets, spill, differential.

Covers the ISSUE 3 acceptance surface: slab reuse + size-class alignment,
typed budget exhaustion (:class:`HbmBudgetExceeded`), bit-exact
spill→fault-back round trips (raw payloads AND through the join
build-index cache), and differential runs of TPC-DS queries under a tiny
``SRJT_HBM_BUDGET`` — budgeted results must match unbudgeted bit-for-bit
while recording at least one spill.
"""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from benchmarks import tpcds_data
from spark_rapids_jni_tpu.memory import (HbmBudgetExceeded, arena, budget,
                                         spill)
from spark_rapids_jni_tpu.models import tpcds
from spark_rapids_jni_tpu.utils import metrics


@pytest.fixture(autouse=True)
def _arena_sandbox():
    """Each test starts with a clean, ENABLED arena and leaves no trace:
    env knobs, ledgers, pools, registry and metrics all restored."""
    saved = {k: os.environ.get(k)
             for k in ("SRJT_HBM_ARENA", "SRJT_HBM_BUDGET",
                       "SRJT_INDEX_CACHE_CAP", "SRJT_ARENA_ZEROS_CAP")}
    os.environ["SRJT_HBM_ARENA"] = "1"
    os.environ.pop("SRJT_HBM_BUDGET", None)
    budget.set_enabled(None)
    arena.reset()
    spill.reset()
    budget.reset()
    metrics.reset()
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    arena.reset()
    spill.reset()
    budget.reset()
    metrics.reset()
    metrics.set_enabled(None)
    budget.set_enabled(None)
    from spark_rapids_jni_tpu.ops import join_plan
    join_plan._INDEX_CACHE.clear()


# --- size classes / slab pool -----------------------------------------------


def test_size_class_rounding():
    assert arena.size_class(1) == 256          # floor
    assert arena.size_class(256) == 256
    assert arena.size_class(257) == 512
    assert arena.size_class(1000) == 1024
    assert arena.size_class(1 << 20) == 1 << 20
    for n in (3, 900, 5000, 123456):
        cls = arena.size_class(n)
        assert cls >= n and cls % 256 == 0     # alignment invariant


def test_slab_identity_reuse():
    s1 = arena.alloc(1000, tag="t")
    assert s1.nbytes == 1024 and s1.data.nbytes == 1024
    buf = s1.data
    arena.free(s1)
    s2 = arena.alloc(900, tag="t")             # same size class → same slab
    assert s2.data is buf
    arena.free(s2)
    assert arena.stats()["pooled_bytes"] == 1024
    assert arena.trim() == 1024
    assert arena.stats()["pooled_bytes"] == 0


def test_double_free_is_noop():
    s = arena.alloc(256)
    arena.free(s)
    arena.free(s)
    assert arena.stats()["pooled_bytes"] == 256


def test_zeros_pooling_identity():
    a = arena.zeros(128, jnp.int32)
    b = arena.zeros(128, jnp.int32)
    assert a is b
    assert not np.asarray(a).any()
    c = arena.zeros((128,), jnp.int64)
    assert c is not a


# --- budgets ----------------------------------------------------------------


def test_parse_bytes():
    assert budget.parse_bytes("512") == 512
    assert budget.parse_bytes("4k") == 4096
    assert budget.parse_bytes("2m") == 2 << 20
    assert budget.parse_bytes("1g") == 1 << 30
    assert budget.parse_bytes("1.5k") == 1536
    assert budget.parse_bytes("") is None
    assert budget.parse_bytes("none") is None
    assert budget.parse_bytes(4096) == 4096


def test_budget_exhaustion_raises_typed():
    os.environ["SRJT_HBM_BUDGET"] = "4k"
    with pytest.raises(HbmBudgetExceeded) as ei:
        arena.alloc(1 << 20, tag="big")        # strict admission
    err = ei.value
    assert err.requested == 1 << 20
    assert err.limit == 4096
    assert err.tag == "arena.big"
    assert budget.in_use() == 0                # denied charge rolled back


def test_soft_reserve_completes_over_budget():
    os.environ["SRJT_HBM_BUDGET"] = "1k"
    metrics.set_enabled(True)
    with arena.reserve(1 << 20, tag="join.expand"):
        assert budget.in_use() == 1 << 20      # stands over-limit
    assert budget.in_use() == 0
    snap = metrics.snapshot()["counters"]
    assert snap.get("arena.budget.soft_over", 0) >= 1


def test_query_budget_scopes_limit():
    with budget.query_budget("q", limit_bytes="2k") as q:
        assert budget.limit_now() == 2048
        with pytest.raises(HbmBudgetExceeded) as ei:
            arena.alloc(8192, tag="x")
        assert ei.value.query == "q"
        assert q.peak == 0                     # denied charge left no peak
    assert budget.limit_now() is None


def test_reserve_noop_when_disabled():
    budget.set_enabled(False)
    assert arena.reserve(1 << 30) is arena.reserve(1 << 30)  # shared no-op
    with arena.reserve(1 << 30):
        assert budget.in_use() == 0


# --- spill / fault-back -----------------------------------------------------


def test_spill_faultback_bit_exact():
    rng = np.random.default_rng(0)
    payloads = {
        "i64": jnp.asarray(rng.integers(-2**62, 2**62, 1000, dtype=np.int64)),
        "u32": jnp.asarray(rng.integers(0, 2**32, 777, dtype=np.uint32)
                           .reshape(-1, 7)),
        "none": None,
    }
    want = {k: (None if v is None else np.asarray(v))
            for k, v in payloads.items()}
    sp = spill.SpillableArrays("t", payloads)
    assert not sp.spilled
    freed = sp.spill()
    assert sp.spilled and freed == sp.nbytes > 0
    assert sp.spill() == 0                     # idempotent
    back = sp.get()
    assert not sp.spilled
    for k, w in want.items():
        if w is None:
            assert back[k] is None
        else:
            np.testing.assert_array_equal(np.asarray(back[k]), w)


def test_reclaim_spills_lru_first():
    os.environ["SRJT_HBM_BUDGET"] = "1m"
    order = []
    a1 = spill.SpillableArrays("a", {"x": jnp.arange(100)})
    a2 = spill.SpillableArrays("b", {"x": jnp.arange(200)})
    spill.register("k1", a1.nbytes, "a",
                   lambda: (order.append("k1"), a1.spill())[1])
    spill.register("k2", a2.nbytes, "b",
                   lambda: (order.append("k2"), a2.spill())[1])
    spill.touch("k1")                          # k2 becomes LRU
    freed = spill.reclaim(1)
    assert order == ["k2"] and freed > 0
    assert spill.resident_count() == 1


def test_join_index_spill_faultback_identical():
    """Force the cached build index to spill; the next join must fault it
    back and produce identical indices (and identity on the hit after)."""
    from spark_rapids_jni_tpu.ops import join_plan
    keys = jnp.asarray(np.arange(4096, dtype=np.int64) % 97)
    ix1 = join_plan.build_index(keys, None, True)
    assert join_plan.build_index(keys, None, True) is ix1   # plain hit
    assert spill.resident_count() == 1
    assert spill.reclaim(1) > 0                # spill the resident
    ix2 = join_plan.build_index(keys, None, True)
    assert ix2 is not ix1
    assert (ix2.kind, ix2.n_valid, ix2.kmin, ix2.span, ix2.unique) == \
           (ix1.kind, ix1.n_valid, ix1.kmin, ix1.span, ix1.unique)
    for lane in ("row_ids", "sorted_keys", "lut_lo", "lut_cnt"):
        a, b = getattr(ix1, lane), getattr(ix2, lane)
        if a is None:
            assert b is None
        else:
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert join_plan.build_index(keys, None, True) is ix2   # identity again


def test_index_cache_capacity_eviction():
    from spark_rapids_jni_tpu.ops import join_plan
    os.environ["SRJT_INDEX_CACHE_CAP"] = "1k"
    metrics.set_enabled(True)
    k1 = jnp.asarray(np.arange(4096, dtype=np.int64) % 31)
    k2 = jnp.asarray(np.arange(4096, dtype=np.int64) % 13)
    join_plan.build_index(k1, None, True)
    join_plan.build_index(k2, None, True)      # over cap → k1 evicted
    assert metrics.snapshot()["counters"].get(
        "join.build_index.evictions", 0) >= 1
    assert join_plan._INDEX_CACHE.device_bytes() <= \
        join_plan._index_nbytes(join_plan.build_index(k2, None, True))


# --- differential: TPC-DS under a tiny budget -------------------------------


@pytest.fixture(scope="module")
def _tpcds_tables():
    files = tpcds_data.generate(n_sales=20_000, n_items=300, seed=11)
    return tpcds.load_tables(files)


@pytest.mark.parametrize("qname", ["q3", "q42", "q52"])
def test_tpcds_differential_under_tiny_budget(_tpcds_tables, qname):
    from spark_rapids_jni_tpu.ops import join_plan
    tables = _tpcds_tables
    # budgeted run FIRST (cold caches: the sandbox fixture cleared the
    # index cache and spill registry) — each query joins twice, so the
    # second join's resident registration pushes past the (deliberately
    # absurd) 256-byte budget and spills the first join's cached index
    join_plan._INDEX_CACHE.clear()
    os.environ["SRJT_HBM_BUDGET"] = "256"
    budget.set_enabled(None)
    assert budget.active()
    metrics.set_enabled(True)
    with budget.query_budget(qname):
        got = tpcds.QUERIES[qname](tables)
    snap = metrics.snapshot()["counters"]
    assert snap.get("arena.spill.events", 0) >= 1, snap

    budget.set_enabled(False)
    metrics.set_enabled(False)
    expect = tpcds.QUERIES[qname](tables)
    assert got.num_rows == expect.num_rows
    for i in range(len(expect.columns)):
        a, b = expect[i], got[i]
        if a.dtype.id.name == "STRING":
            assert a.to_pylist() == b.to_pylist()
        else:
            np.testing.assert_array_equal(a.to_numpy(), b.to_numpy())
