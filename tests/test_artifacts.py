"""AOT plan-artifact store (exec/artifacts.py): zero-compile cold start.

The store's contract is "never wrong, only slower": a persisted artifact
either rehydrates a plan with ZERO capture runs and bit-identical
results, or degrades to the ordinary live capture — corrupted files,
version skew, and stale tapes are all misses, never errors.  These tests
hold every leg:

* round-trip — tape serialize/deserialize bit-identity (including >2^32
  sizes), atomic files, manifest ranking.
* geometry — pow2 bucketing folds nearby dataset sizes onto one key,
  exact mode keeps them apart, opaque objects make the key unstable and
  unpersistable.
* fallback — corrupted artifact and env/version skew fall back to live
  capture with an ``aot.reject`` count; a stale tape (same bucket,
  different resolved sizes) raises through the checked run into a
  recapture whose write-back overwrites the artifact.
* integration — a populated store serves a fresh PlanCache (and a full
  QueryScheduler) with ``compiled.capture == 0``; the scheduler warm-up
  thread pre-hydrates manifest entries at startup.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spark_rapids_jni_tpu import types as T
from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.exec import artifacts
from spark_rapids_jni_tpu.exec.plan_cache import PlanCache
from spark_rapids_jni_tpu.ops import filter as F
from spark_rapids_jni_tpu.utils import metrics


@pytest.fixture(autouse=True)
def _metrics_on():
    metrics.set_enabled(True)
    metrics.reset()
    yield
    metrics.reset()
    metrics.set_enabled(None)


@pytest.fixture
def aot_dir(tmp_path, monkeypatch):
    d = str(tmp_path / "aot")
    monkeypatch.setenv("SRJT_AOT_DIR", d)
    return d


def _mktab(n, seed=7):
    rng = np.random.default_rng(seed)
    return {"t": Table([
        Column(T.DType(T.TypeId.INT32),
               jnp.asarray(rng.integers(0, 50, n).astype(np.int32))),
        Column(T.DType(T.TypeId.FLOAT32),
               jnp.asarray(rng.standard_normal(n).astype(np.float32)))])}


def _q_filter(tbls):
    # tape-bearing query: the compaction count resolves through the
    # syncs funnel, so the capture tape is non-empty and data-determined
    t = tbls["t"]
    return F.apply_boolean_mask(t, t.columns[0].data < 25)


def _canon(result):
    return [np.asarray(l) for l in jax.tree_util.tree_leaves(result)]


def _same(a, b):
    return len(a) == len(b) and all(
        x.shape == y.shape and np.array_equal(x, y)
        for x, y in zip(a, b))


# --- round-trip --------------------------------------------------------------


def test_tape_roundtrip_bit_identity(aot_dir):
    store = artifacts.get_store()
    geom = artifacts.geometry_key(_mktab(100))
    tape = (0, 1, 3, 2**40 + 17, 7)     # >2^32: JSON ints stay exact
    assert store.put("planA", "v1", geom, tape, name="qa", cost_ms=9.5)
    assert store.lookup("planA", "v1", geom) == tape
    # the on-disk document is plain versioned JSON, bit-exact through a
    # cold read (drop the in-memory copy first)
    store._mem.clear()
    assert store.lookup("planA", "v1", geom) == tape
    with open(store.path_for("planA", "v1", geom)) as f:
        doc = json.load(f)
    assert doc["version"] == artifacts.STORE_VERSION
    assert tuple(doc["tape"]) == tape
    assert doc["env"] == artifacts.env_fingerprint()


def test_manifest_ranked_by_cost(aot_dir):
    store = artifacts.get_store()
    geom = artifacts.geometry_key(_mktab(100))
    store.put("cheap", "", geom, (1,), cost_ms=2.0)
    store.put("dear", "", geom, (2,), cost_ms=50.0)
    store.put("mid", "", geom, (3,), cost_ms=10.0)
    assert [e["plan"] for _, e in store.manifest_entries()] == \
        ["dear", "mid", "cheap"]


def test_variant_and_key_isolation(aot_dir):
    store = artifacts.get_store()
    geom = artifacts.geometry_key(_mktab(100))
    store.put("p", "", geom, (1, 2))
    assert store.lookup("p", "sorted", geom) is None
    assert store.lookup("other", "", geom) is None
    assert store.lookup("p", "", geom) == (1, 2)


# --- geometry keys -----------------------------------------------------------


def test_geometry_pow2_bucketing():
    a, b = _mktab(900), _mktab(1000)
    # both bucket to 1024 → shared artifact key
    assert artifacts.geometry_key(a, buckets=True) == \
        artifacts.geometry_key(b, buckets=True)
    # exact mode keeps them apart
    assert artifacts.geometry_key(a, buckets=False) != \
        artifacts.geometry_key(b, buckets=False)
    # a true bucket boundary still separates (1024 → 1024, 1025 → 2048)
    assert artifacts.geometry_key(_mktab(1024), buckets=True) != \
        artifacts.geometry_key(_mktab(1025), buckets=True)
    # dtype is part of the geometry even inside one bucket
    c = _mktab(1000)
    c["t"].columns[0].data = c["t"].columns[0].data.astype(jnp.int64)
    assert artifacts.geometry_key(c, buckets=True) != \
        artifacts.geometry_key(b, buckets=True)


def test_geometry_unstable_for_opaque_objects():
    class Opaque:
        pass
    tables = {"t": _mktab(64)["t"], "cfg": Opaque()}
    # id()-keyed entries are process-local: no stable cross-process key
    assert artifacts.geometry_key(tables) is None
    assert metrics.counter_value("aot.unstable_key") >= 1


# --- fallback: corrupt / skew / stale ---------------------------------------


def test_corrupt_artifact_degrades_to_capture(aot_dir):
    store = artifacts.get_store()
    tables = _mktab(500)
    pc = PlanCache()
    out = _canon(pc.run("qf", _q_filter, tables))
    geom = artifacts.geometry_key(tables)
    path = store.path_for("qf", "", geom)
    assert os.path.exists(path)
    with open(path, "w") as f:
        f.write('{"version": 1, "tape": [1, 2')     # torn write simulation
    store._mem.clear()
    metrics.reset()
    out2 = _canon(PlanCache().run("qf", _q_filter, tables))
    assert _same(out, out2)
    assert metrics.counter_value("compiled.capture") == 1   # live fallback
    assert metrics.counter_value("compiled.rehydrate") == 0
    assert metrics.counter_value("aot.reject") >= 1
    # the recapture's write-back healed the artifact in place
    store._mem.clear()
    assert store.lookup("qf", "", geom) is not None


def test_version_skew_rejected(aot_dir):
    store = artifacts.get_store()
    geom = artifacts.geometry_key(_mktab(100))
    store.put("p", "", geom, (5, 6))
    path = store.path_for("p", "", geom)
    with open(path) as f:
        doc = json.load(f)
    doc["env"] = "store1;jax0.0.0;pkg0.0.0"
    with open(path, "w") as f:
        json.dump(doc, f)
    store._mem.clear()
    assert store.lookup("p", "", geom) is None
    assert metrics.counter_value("aot.reject") >= 1
    doc["env"] = artifacts.env_fingerprint()
    doc["version"] = artifacts.STORE_VERSION + 1
    with open(path, "w") as f:
        json.dump(doc, f)
    assert store.lookup("p", "", geom) is None


def test_stale_tape_rehydrate_recaptures(aot_dir):
    # an artifact whose tape disagrees with the live data's resolved
    # sizes must degrade to a live capture with identical results — and
    # its write-back overwrites the stale artifact for the next process
    store = artifacts.get_store()
    tables = _mktab(500)
    geom = artifacts.geometry_key(tables)
    store.put("qf", "", geom, (3,))             # wrong resolved size
    out = _canon(PlanCache().run("qf", _q_filter, tables))
    assert _same(out, _canon(_q_filter(tables)))
    assert metrics.counter_value("compiled.rehydrate") == 1
    assert metrics.counter_value("exec.plan_cache.stale") == 1
    assert metrics.counter_value("compiled.capture") == 1
    # healed: a fresh cache now rehydrates with zero captures
    metrics.reset()
    store._mem.clear()
    out2 = _canon(PlanCache().run("qf", _q_filter, tables))
    assert _same(out, out2)
    assert metrics.counter_value("compiled.capture") == 0
    assert metrics.counter_value("compiled.rehydrate") == 1


def test_stale_wrong_length_tape_recaptures(aot_dir):
    # replay RuntimeErrors (tape too short/long for the plan's resolution
    # sites) must surface as StaleTapeError → recapture, not crash
    tables = _mktab(500)
    geom = artifacts.geometry_key(tables)
    store = artifacts.get_store()
    store.put("qf", "", geom, ())               # empty tape, plan has syncs
    out = _canon(PlanCache().run("qf", _q_filter, tables))
    assert _same(out, _canon(_q_filter(tables)))
    assert metrics.counter_value("exec.plan_cache.stale") == 1
    assert metrics.counter_value("compiled.capture") == 1


# --- integration: plan cache + scheduler ------------------------------------


def test_plan_cache_zero_capture_from_store(aot_dir):
    tables = _mktab(500)
    oracle = _canon(PlanCache().run("qf", _q_filter, tables))
    assert metrics.counter_value("compiled.capture") == 1
    assert metrics.counter_value("aot.write") == 1
    # fresh cache, populated store: the cold-start contract is ZERO
    # capture runs and bit-identical results
    metrics.reset()
    pc = PlanCache()
    out = _canon(pc.run("qf", _q_filter, tables))
    assert _same(oracle, out)
    assert metrics.counter_value("compiled.capture") == 0
    assert metrics.counter_value("compiled.rehydrate") == 1
    assert metrics.counter_value("exec.plan_cache.aot_hit") == 1
    # the rehydrated plan's ledger carries cold-start attribution
    # (CompiledQuery keys the ledger on the query function's name)
    led = metrics.ledger_snapshot().get("_q_filter", {})
    assert led.get("rehydrates") == 1
    assert "captures" not in led


def test_scheduler_serves_zero_capture_and_warms_up(aot_dir, monkeypatch):
    from spark_rapids_jni_tpu import exec as xc
    monkeypatch.setenv("SRJT_AOT_WARMUP", "4")
    tables = _mktab(800)
    with xc.QueryScheduler(workers=2) as sched:
        oracle = _canon(sched.run("qf", _q_filter, tables))
    assert metrics.counter_value("compiled.capture") == 1
    metrics.reset()
    artifacts.get_store()._mem.clear()
    with xc.QueryScheduler(workers=2) as sched:
        # the startup warm-up thread pre-hydrates the manifest entries
        assert sched._warmup_thread is not None
        sched._warmup_thread.join(timeout=30)
        assert metrics.counter_value("aot.preloaded") >= 1
        out = _canon(sched.run("qf", _q_filter, tables))
    assert _same(oracle, out)
    assert metrics.counter_value("compiled.capture") == 0
    assert metrics.counter_value("compiled.rehydrate") == 1


def test_disabled_store_writes_nothing(tmp_path, monkeypatch):
    monkeypatch.delenv("SRJT_AOT_DIR", raising=False)
    assert not artifacts.enabled()
    assert artifacts.get_store() is None
    tables = _mktab(300)
    out = _canon(PlanCache().run("qf", _q_filter, tables))
    assert _same(out, _canon(_q_filter(tables)))
    assert metrics.counter_value("aot.write") == 0
    assert list(tmp_path.iterdir()) == []
