"""Differential suite for the zero-copy ETL→ML handoff (``ml/``).

The contracts under test, each against an independent host oracle:

* feature pack bit-identity — every lane (ints, decimals, f64 bit-pairs,
  bool, dict-string categoricals, null imputation) must match a numpy
  oracle BIT FOR BIT, through both pack engines (``rowconv`` row-stream
  reinterpretation and the ``stack`` reference);
* categorical ids without byte materialization — a DictColumn feature
  packs through its dictionary only (``strings.dict.materialize`` == 0);
* train-step parity — the jitted SGD/Adam steps against a float32 numpy
  reference fed the identical shuffled batches;
* zero steady-state syncs — after one warm epoch, N further epochs
  dispatch with ``syncs.sync_count()`` delta of exactly zero;
* capture/replay — a feature plan compiled via ``models/compiled.py``
  replays bit-identically (the pack path's one data-dependent sync rides
  the tape);
* predict-through-scheduler bit-identity — including under one injected
  device fault (PR 11 chaos harness);
* online feature store — a FeatureView re-packed by delta refresh equals
  a from-scratch pack of the refreshed view result.
"""

import io

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import jax
import jax.numpy as jnp

from spark_rapids_jni_tpu import exec as xc
from spark_rapids_jni_tpu import ml
from spark_rapids_jni_tpu import types as T
from spark_rapids_jni_tpu.column import Column, DictColumn, Table
from spark_rapids_jni_tpu.faultinj import injector as finj
from spark_rapids_jni_tpu.ml import features as F
from spark_rapids_jni_tpu.models import compiled as C
from spark_rapids_jni_tpu.plan import ir
from spark_rapids_jni_tpu.stream import DeltaTable, ViewRegistry
from spark_rapids_jni_tpu.utils import metrics, syncs


@pytest.fixture(autouse=True)
def _metrics_on():
    metrics.set_enabled(True)
    metrics.reset()
    yield
    finj.get_injector().disable()
    metrics.reset()
    metrics.set_enabled(None)


def _np32(x):
    return np.asarray(x, dtype=np.float32)


def _categorical_oracle(values, impute=-1.0):
    """The documented categorical-id contract: rank among the sorted
    distinct byte strings, where null rows contribute the zeroed (empty)
    key; imputation applies after encoding."""
    distinct = set(v for v in values if v is not None)
    if any(v is None for v in values):
        distinct.add("")
    rank = {v: i for i, v in enumerate(sorted(distinct))}
    return np.array([impute if v is None else rank[v] for v in values],
                    dtype=np.float32)


# --- feature pack bit-identity ----------------------------------------------


class TestFeaturePack:
    def _mixed(self, n=257, seed=0):
        rng = np.random.default_rng(seed)
        i64 = rng.integers(-1000, 1000, n).astype(np.int64)
        i32 = rng.integers(0, 100, n).astype(np.int32)
        i32_null = rng.random(n) < 0.25
        f64 = rng.normal(size=n) * 1e3
        f32 = rng.normal(size=n).astype(np.float32)
        b8 = rng.integers(0, 2, n).astype(bool)
        dec = rng.integers(-10**6, 10**6, n).astype(np.int64)
        strs = [None if rng.random() < 0.2
                else ["red", "green", "blue", "", "aa\x00b"][
                    rng.integers(0, 5)] for _ in range(n)]
        tbl = Table([
            Column.from_numpy(i64),
            Column(T.int32, jnp.asarray(i32),
                   validity=jnp.asarray(~i32_null)),
            Column.from_numpy(f64),
            Column(T.float32, jnp.asarray(f32)),
            Column.from_numpy(b8),
            Column(T.decimal64(-3), jnp.asarray(dec)),
            Column.strings_from_list(strs),
        ])
        names = ["i64", "i32", "f64", "f32", "b8", "dec", "s"]
        host = dict(i64=i64, i32=i32, i32_null=i32_null, f64=f64, f32=f32,
                    b8=b8, dec=dec, strs=strs)
        return tbl, names, host

    def _oracle(self, host):
        i32 = host["i32"].astype(np.float32)
        null = host["i32_null"]
        # mean imputation: f64 accumulation over the valid int values —
        # exact, order-independent
        mean = np.float32(host["i32"][~null].astype(np.float64).mean())
        i32 = np.where(null, mean, i32).astype(np.float32)
        return np.stack([
            host["i64"].astype(np.float32),
            i32,
            host["f64"].astype(np.float64).astype(np.float32),
            host["f32"],
            host["b8"].astype(np.float32),
            host["dec"].astype(np.float32) * np.float32(10.0 ** -3),
            _categorical_oracle(host["strs"]),
        ], axis=1)

    def _spec(self):
        return F.FeatureSpec.of([
            F.Feature("i64"), F.Feature("i32", impute="mean"),
            F.Feature("f64"), F.Feature("f32"), F.Feature("b8"),
            F.Feature("dec"), F.Feature("s", impute=("const", -1.0)),
        ])

    def test_bit_identical_to_numpy_oracle(self):
        tbl, names, host = self._mixed()
        fb = self._spec().pack(tbl, names)
        assert fb.X.dtype == jnp.float32
        np.testing.assert_array_equal(_np32(fb.X), self._oracle(host))

    def test_engines_bit_identical(self):
        tbl, names, _ = self._mixed(seed=3)
        spec = self._spec()
        a = spec.pack(tbl, names, engine="rowconv")
        b = spec.pack(tbl, names, engine="stack")
        np.testing.assert_array_equal(_np32(a.X), _np32(b.X))

    def test_multi_batch_rowconv_pack(self):
        # tiny batch cap forces >1 RowBatch through the matrix reslice
        n = 300
        rng = np.random.default_rng(7)
        vals = rng.normal(size=(n, 3)).astype(np.float32)
        tbl = Table([Column(T.float32, jnp.asarray(vals[:, i]))
                     for i in range(3)])
        from spark_rapids_jni_tpu.rowconv import convert as RC
        from spark_rapids_jni_tpu.rowconv.layout import compute_row_layout
        layout = compute_row_layout(tbl.schema)
        batches = RC.convert_to_rows(tbl, max_batch_bytes=
                                     layout.fixed_row_size * 64)
        assert len(batches) > 1
        mats = [RC.fixed_rows_to_matrix(b, layout) for b in batches]
        np.testing.assert_array_equal(
            _np32(jnp.concatenate(mats, axis=0)), vals)

    def test_dict_categorical_never_materializes(self):
        # dict-path id contract: rank over the DICTIONARY's distinct
        # values (nulls collapse onto code 0 but impute away); the
        # plain-string path additionally ranks the null/zeroed key —
        # the two representations agree exactly on null-free columns
        strs = ["b", "a", "c", "a", None, "b"] * 40
        codes = jnp.asarray(np.array([1, 0, 2, 0, 0, 1] * 40, np.int32))
        dcol = DictColumn(codes, Column.strings_from_list(["a", "b", "c"]),
                          validity=jnp.asarray(
                              np.array([s is not None for s in strs])))
        spec = F.FeatureSpec.of([F.Feature("s", impute=("const", -1.0))])
        before = metrics.counter_value("strings.dict.materialize")
        fb = spec.pack(Table([dcol]), ["s"])
        assert metrics.counter_value("strings.dict.materialize") == before
        rank = {"a": 0.0, "b": 1.0, "c": 2.0}
        np.testing.assert_array_equal(
            _np32(fb.X)[:, 0],
            np.array([-1.0 if s is None else rank[s] for s in strs],
                     np.float32))

    def test_dict_and_plain_paths_agree_when_null_free(self):
        strs = ["b", "a", "c", "a", "c", "b"] * 40
        codes = jnp.asarray(np.array([1, 0, 2, 0, 2, 1] * 40, np.int32))
        dcol = DictColumn(codes, Column.strings_from_list(["a", "b", "c"]))
        spec = F.FeatureSpec.of([F.Feature("s")])
        a = spec.pack(Table([dcol]), ["s"])
        b = spec.pack(Table([Column.strings_from_list(strs)]), ["s"])
        np.testing.assert_array_equal(_np32(a.X), _np32(b.X))
        np.testing.assert_array_equal(_np32(a.X)[:, 0],
                                      _categorical_oracle(strs))

    def test_imputation_policies(self):
        vals = np.array([1, -2, 3, 4, 5], np.int64)
        valid = np.array([True, False, True, False, True])
        col = Column(T.int64, jnp.asarray(vals), validity=jnp.asarray(valid))
        for policy, fill in (("zero", 0.0), (("const", 9.5), 9.5)):
            fb = F.FeatureSpec.of([F.Feature("v", impute=policy)]).pack(
                Table([col]), ["v"])
            oracle = np.where(valid, vals.astype(np.float32),
                              np.float32(fill))
            np.testing.assert_array_equal(_np32(fb.X)[:, 0], oracle)
        fb = F.FeatureSpec.of([F.Feature("v", impute="mean")]).pack(
            Table([col]), ["v"])
        mean = np.float32(vals[valid].astype(np.float64).mean())
        np.testing.assert_array_equal(
            _np32(fb.X)[:, 0],
            np.where(valid, vals.astype(np.float32), mean))

    def test_nullable_without_policy_is_an_error(self):
        col = Column(T.int64, jnp.asarray(np.arange(4)),
                     validity=jnp.asarray([True, False, True, True]))
        with pytest.raises(ValueError, match="imputation"):
            F.FeatureSpec.of([F.Feature("v")]).pack(Table([col]), ["v"])

    def test_label_binarization(self):
        y = np.array([0, 1, 3, 0, 2], np.int64)
        tbl = Table([Column.from_numpy(np.arange(5, dtype=np.int64)),
                     Column.from_numpy(y)])
        spec = F.FeatureSpec.of([F.Feature("x")], label="d",
                                label_transform=("gt", 0.0))
        fb = spec.pack(tbl, ["x", "d"])
        np.testing.assert_array_equal(_np32(fb.y),
                                      (y > 0).astype(np.float32))
        # serving packs features-only from the same spec
        fb2 = spec.pack(Table([tbl[0]]), ["x"], with_label=False)
        assert fb2.y is None and fb2.X.shape == (5, 1)


# --- train-step parity vs numpy ---------------------------------------------


def _numpy_sgd_logreg(batches, lr, epochs_batches):
    """float32 numpy reference of the jitted logistic/SGD step."""
    k = batches[0][0].shape[1]
    w = np.zeros(k, np.float32)
    b = np.float32(0.0)
    vw = np.zeros(k, np.float32)
    vb = np.float32(0.0)
    mu = np.float32(0.9)
    lr = np.float32(lr)
    for xb, yb in batches:
        z = xb @ w + b
        p = 1.0 / (1.0 + np.exp(-z.astype(np.float64)))
        g = (p.astype(np.float32) - yb) / np.float32(xb.shape[0])
        gw = xb.T @ g
        gb = g.sum(dtype=np.float32)
        vw = mu * vw + gw
        vb = mu * vb + gb
        w = w - lr * vw
        b = b - lr * vb
    return w, b


class TestTrainParity:
    def _pipe(self, n=512, k=3, seed=4, batch=64):
        rng = np.random.default_rng(seed)
        X = rng.normal(size=(n, k)).astype(np.float32)
        y = (X @ rng.normal(size=k).astype(np.float32) > 0).astype(
            np.float32)
        fb = F.FeatureBatch(jnp.asarray(X), jnp.asarray(y))
        return ml.BatchPipeline(fb, batch_size=batch, seed=seed)

    def test_logreg_sgd_matches_numpy(self):
        pipe = self._pipe()
        tr = ml.Trainer(ml.logistic_regression(), ml.sgd(lr=0.3,
                                                         momentum=0.9))
        params, ostate = tr.init(pipe.k)
        host_batches = []
        for e in range(3):
            Xb, yb = pipe.epoch_arrays(e)
            host_batches += [(np.asarray(Xb[i]), np.asarray(yb[i]))
                             for i in range(pipe.num_batches)]
            params, ostate, _ = tr.run_epoch(params, ostate, Xb, yb)
        w, b = _numpy_sgd_logreg(host_batches, 0.3, None)
        np.testing.assert_allclose(np.asarray(params["w"]), w,
                                   rtol=2e-4, atol=2e-5)
        np.testing.assert_allclose(float(params["b"]), b,
                                   rtol=2e-4, atol=2e-5)

    def test_adam_linreg_converges_and_matches_reference(self):
        pipe = self._pipe(seed=9)
        tr = ml.Trainer(ml.linear_regression(), ml.adam(lr=0.05))
        res = tr.fit(pipe, epochs=12)
        assert res.losses[-1] < res.losses[0]
        # rerunning from scratch is deterministic
        res2 = ml.Trainer(ml.linear_regression(),
                          ml.adam(lr=0.05)).fit(pipe, epochs=12)
        np.testing.assert_array_equal(res.losses, res2.losses)
        np.testing.assert_array_equal(np.asarray(res.params["w"]),
                                      np.asarray(res2.params["w"]))

    def test_fused_and_unfused_epochs_agree(self):
        pipe = self._pipe(seed=11, batch=128)
        a = ml.Trainer(ml.logistic_regression(), ml.sgd(lr=0.1),
                       fuse=True).fit(pipe, epochs=2)
        b = ml.Trainer(ml.logistic_regression(), ml.sgd(lr=0.1),
                       fuse=False).fit(pipe, epochs=2)
        np.testing.assert_allclose(np.asarray(a.params["w"]),
                                   np.asarray(b.params["w"]),
                                   rtol=1e-6, atol=1e-7)


# --- the zero-sync steady loop ----------------------------------------------


class TestSteadyLoop:
    def test_zero_syncs_across_steady_epochs(self):
        rng = np.random.default_rng(2)
        X = rng.normal(size=(1024, 4)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        pipe = ml.BatchPipeline(
            F.FeatureBatch(jnp.asarray(X), jnp.asarray(y)),
            batch_size=128, seed=1)
        tr = ml.Trainer(ml.logistic_regression(), ml.adam(lr=0.01))
        params, ostate = tr.init(pipe.k)
        Xb, yb = pipe.epoch_arrays(0)           # warm epoch compiles
        params, ostate, loss = tr.run_epoch(params, ostate, Xb, yb)
        loss.block_until_ready()
        base = syncs.sync_count()
        for e in range(1, 5):
            Xb, yb = pipe.epoch_arrays(e)
            params, ostate, loss = tr.run_epoch(params, ostate, Xb, yb)
        assert syncs.sync_count() - base == 0, \
            "steady batch loop must not sync the host"
        assert np.isfinite(float(loss))

    def test_shuffle_is_deterministic_per_epoch(self):
        X = jnp.asarray(np.arange(40, dtype=np.float32).reshape(20, 2))
        y = jnp.zeros(20, jnp.float32)
        p1 = ml.BatchPipeline(F.FeatureBatch(X, y), batch_size=5, seed=42)
        p2 = ml.BatchPipeline(F.FeatureBatch(X, y), batch_size=5, seed=42)
        a, b = p1.epoch_arrays(3), p2.epoch_arrays(3)
        np.testing.assert_array_equal(np.asarray(a[0]), np.asarray(b[0]))
        c = p1.epoch_arrays(4)
        assert not np.array_equal(np.asarray(a[0]), np.asarray(c[0]))
        # every epoch visits a permutation: sorted rows == sorted input
        np.testing.assert_array_equal(
            np.sort(np.asarray(a[0]).reshape(20, 2), axis=0),
            np.sort(np.asarray(X), axis=0))

    def test_both_shuffle_engines_are_permutations(self):
        n = 203                       # odd size exercises feistel compaction
        X = jnp.asarray(np.arange(n, dtype=np.float32).reshape(n, 1))
        y = jnp.zeros(n, jnp.float32)
        for eng in ("feistel", "sort"):
            pipe = ml.BatchPipeline(F.FeatureBatch(X, y), batch_size=n,
                                    seed=9, shuffle=eng)
            rows = np.asarray(pipe.epoch_arrays(2)[0]).reshape(n)
            assert not np.array_equal(rows, np.arange(n)), eng
            np.testing.assert_array_equal(np.sort(rows), np.arange(n),
                                          err_msg=eng)


# --- capture/replay ----------------------------------------------------------


class TestCaptureReplay:
    def test_feature_plan_roundtrip(self):
        n = 200
        rng = np.random.default_rng(5)
        strs = [["x", "y", "zz", None][i % 4] for i in range(n)]
        tbl = Table([Column.from_numpy(
                         rng.integers(0, 9, n).astype(np.int32)),
                     Column.strings_from_list(strs)])
        tables = {"t": tbl}
        spec = F.FeatureSpec.of([F.Feature("a"),
                                 F.Feature("s", impute=("const", -1.0))])
        tree = ir.Filter(ir.Scan("t"),
                         ir.Cmp(">", ir.Col("a"), ir.Lit(2)))
        qfn = F.compile_feature_plan(tree, {"t": ["a", "s"]}, spec,
                                     with_label=False)
        assert qfn.plan_fingerprint.endswith(":ml.features")
        eager = qfn(tables)
        cq = C.compile_query(qfn, tables)
        for _ in range(2):
            got = cq.run(tables)
            np.testing.assert_array_equal(_np32(eager.X), _np32(got.X))


# --- predict through the scheduler ------------------------------------------


def _servable(seed=1, n=512):
    rng = np.random.default_rng(seed)
    tbl = Table([Column.from_numpy(
                     rng.integers(0, 50, n).astype(np.int64)),
                 Column(T.float32, jnp.asarray(
                     rng.normal(size=n).astype(np.float32)))])
    tables = {"t": tbl}
    spec = F.FeatureSpec.of([F.Feature("a"), F.Feature("b")])
    params = {"w": jnp.asarray(rng.normal(size=2).astype(np.float32)),
              "b": jnp.float32(0.25)}
    sv = ml.ServableModel.from_plan(f"sv{seed}", ir.Scan("t"),
                                    {"t": ["a", "b"]}, spec,
                                    ml.logistic_regression(), params)
    return sv, tables


class TestServe:
    def test_predict_through_scheduler_bit_identical(self):
        sv, tables = _servable(seed=21)
        ml.register_servable(sv)
        assert sv.name in ml.servables()
        oracle = np.asarray(sv.predict_table(tables)[0].data)
        with xc.QueryScheduler(workers=2, devices=2) as sched:
            got = [sched.submit_predict(sv.name, tables).result(timeout=60)
                   for _ in range(4)]
        for t in got:
            np.testing.assert_array_equal(np.asarray(t[0].data), oracle)

    def test_predict_bit_identical_under_device_fault(self):
        sv, tables = _servable(seed=22)
        oracle = np.asarray(sv.predict_table(tables)[0].data)
        inj = finj.get_injector()
        assert len(jax.devices()) >= 4
        with xc.QueryScheduler(workers=4, devices=4, probe_base_s=0.02,
                               probe_max_s=0.2) as sched:
            inj.load_dict({"seed": 1, "sites": {
                "exec.dispatch": {"percent": 100,
                                  "injectionType": "device_error",
                                  "maxHits": 1}}})
            inj.enable()
            tickets = [sched.submit_predict(sv, tables) for _ in range(8)]
            for tk in tickets:
                np.testing.assert_array_equal(
                    np.asarray(tk.result(timeout=120)[0].data), oracle)
            assert inj.injected_count == 1
            assert any(tk.relocations > 0 for tk in tickets), \
                "no predict request failed over"


# --- online feature store ----------------------------------------------------


def _blob(n, start=0):
    tab = pa.table({
        "k": pa.array(np.arange(start, start + n, dtype=np.int32)),
        "v": pa.array((np.arange(start, start + n) * 3).astype(np.int64)),
    })
    buf = io.BytesIO()
    pq.write_table(tab, buf, row_group_size=4, use_dictionary=False)
    return buf.getvalue()


class TestFeatureView:
    def test_online_refresh_matches_full_recompute(self):
        delta = DeltaTable("f", files=[_blob(16)])
        reg = ViewRegistry(delta, {}, {})
        plan = ir.Aggregate(ir.Scan("f"), ("k",),
                            (("v", "sum", "sv"), ("v", "count", "nv")))
        spec = F.FeatureSpec.of([F.Feature("k"), F.Feature("sv")],
                                label="nv")
        fv = ml.FeatureView(reg, plan, spec)
        try:
            assert fv.current().num_rows == 16
            for start in (100, 200):
                delta.append_file(_blob(8, start=start))
                fb = fv.refresh()
                oracle = spec.pack(reg.refresh(fv.view), fv.names)
                np.testing.assert_array_equal(_np32(fb.X), _np32(oracle.X))
                np.testing.assert_array_equal(_np32(fb.y), _np32(oracle.y))
            assert metrics.counter_value("stream.refresh.incremental") >= 2
            assert metrics.counter_value("ml.feature_view.repacks") >= 3
        finally:
            fv.close()

    def test_refresh_through_scheduler_repacks(self):
        delta = DeltaTable("f", files=[_blob(12)])
        reg = ViewRegistry(delta, {}, {})
        plan = ir.Aggregate(ir.Scan("f"), ("k",), (("v", "sum", "sv"),))
        spec = F.FeatureSpec.of([F.Feature("k"), F.Feature("sv")])
        fv = ml.FeatureView(reg, plan, spec, with_label=False)
        try:
            fv.refresh()
            delta.append_file(_blob(6, start=500))
            with xc.QueryScheduler(workers=1, devices=1) as sched:
                sched.submit_refresh(reg, fv.view).result(timeout=60)
            assert fv.current().num_rows == 18
        finally:
            fv.close()
