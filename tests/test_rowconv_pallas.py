"""Pallas fixed-width transcode kernels vs the XLA oracle.

The Pallas kernels (``rowconv/pallas_kernels.py``) are the TPU analog of the
reference's tiled CUDA kernels (``row_conversion.cu:575-693, 892-993``); on
CPU CI they run in interpret mode and must be byte-identical to the XLA
path, which itself is differential- and round-trip-tested against the NumPy
and C++ host engines (tests/test_rowconv*.py) — the same oracle chaining the
reference uses between its legacy and tiled paths
(``tests/row_conversion.cpp:49-58``).
"""

import numpy as np
import pytest

import jax.numpy as jnp

import spark_rapids_jni_tpu as sr
from spark_rapids_jni_tpu.rowconv import pallas_kernels as pk
from spark_rapids_jni_tpu.rowconv.convert import (_to_rows_fixed,
                                                  _from_rows_fixed)
from spark_rapids_jni_tpu.rowconv.layout import compute_row_layout

SCHEMAS = {
    "mixed": [sr.int64, sr.int32, sr.float32, sr.int16, sr.int8, sr.bool8],
    "bytes_odd_validity": [sr.int8] * 5,           # validity at offset 5
    "shared_word": [sr.int32, sr.int8],            # i8 + validity in one word
    "all_types": [sr.int8, sr.int16, sr.int32, sr.int64, sr.uint8,
                  sr.uint16, sr.uint32, sr.uint64, sr.float32, sr.float64,
                  sr.bool8, sr.timestamp_ms, sr.timestamp_days,
                  sr.decimal32(-2), sr.decimal64(-4)],
    "wide": [sr.int32, sr.int8, sr.int16, sr.int64] * 16,  # 64 cols, 8 vbytes
}


def _random_inputs(schema, n, seed=0):
    """(datas, valid) in the jit-core staging convention (f64 → u32 [n,2])."""
    rng = np.random.default_rng(seed)
    datas = []
    for dt in schema:
        st = dt.storage
        if st.kind == "f":
            arr = rng.standard_normal(n).astype(st)
            if st.itemsize == 8:
                datas.append(jnp.asarray(arr.view(np.uint32).reshape(-1, 2)))
                continue
        elif dt == sr.bool8:
            arr = rng.integers(0, 2, n).astype(st)
        else:
            info = np.iinfo(st)
            arr = rng.integers(info.min // 2, info.max // 2, n, dtype=st)
        datas.append(jnp.asarray(arr))
    valid = jnp.asarray(rng.random((n, len(schema))) < 0.8)
    return tuple(datas), valid


@pytest.mark.parametrize("name", sorted(SCHEMAS))
@pytest.mark.parametrize("n", [1, 7, 100, 530])
def test_pack_matches_xla_oracle(name, n):
    schema = SCHEMAS[name]
    layout = compute_row_layout(schema)
    datas, valid = _random_inputs(schema, n, seed=hash(name) % 1000)
    want = np.asarray(_to_rows_fixed(layout, datas, valid))
    got = np.asarray(pk.to_rows_fixed(layout, datas, valid, interpret=True))
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("name", sorted(SCHEMAS))
@pytest.mark.parametrize("n", [1, 100, 530])
def test_unpack_matches_xla_oracle(name, n):
    schema = SCHEMAS[name]
    layout = compute_row_layout(schema)
    datas, valid = _random_inputs(schema, n, seed=hash(name) % 1000 + 1)
    rows = np.asarray(_to_rows_fixed(layout, datas, valid))
    want_datas, want_valid = _from_rows_fixed(layout, jnp.asarray(rows))
    got_datas, got_valid = pk.from_rows_fixed(layout, jnp.asarray(rows),
                                              interpret=True)
    assert len(got_datas) == len(want_datas)
    for g, w in zip(got_datas, want_datas):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))
    np.testing.assert_array_equal(np.asarray(got_valid),
                                  np.asarray(want_valid))


@pytest.mark.parametrize("name", sorted(SCHEMAS))
def test_pallas_round_trip(name):
    schema = SCHEMAS[name]
    layout = compute_row_layout(schema)
    datas, valid = _random_inputs(schema, 257, seed=42)
    rows = pk.to_rows_fixed(layout, datas, valid, interpret=True)
    back, valid2 = pk.from_rows_fixed(layout, rows, interpret=True)
    for g, w in zip(back, datas):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))
    np.testing.assert_array_equal(np.asarray(valid2), np.asarray(valid))


def test_dispatch_env_override(monkeypatch):
    monkeypatch.setenv("SRJT_PALLAS", "0")
    assert pk.fixed_pallas_enabled() is False
    monkeypatch.setenv("SRJT_PALLAS", "1")
    assert pk.fixed_pallas_enabled() is True
    # auto on CPU backend: off (cached decision may be None or False)
    monkeypatch.setenv("SRJT_PALLAS", "auto")
    assert pk.fixed_pallas_enabled() is False
