"""Safety/regression tests for the C++ host table engine (host_table.cpp).

Round-1 advisor findings: (a) ``batch_bounds`` infinite-looped when a single
row exceeded the batch byte limit instead of failing like the Python engine
(layout.build_batches raises ValueError); (b) ``srjt_rows_import`` /
``srjt_from_rows`` trusted shuffle-received bytes — offsets and row-embedded
string slots — without bounds checks, allowing out-of-bounds reads.
"""

import ctypes as C
import os

import numpy as np
import pytest

_LIB = os.path.join(os.path.dirname(__file__), "..",
                    "spark_rapids_jni_tpu", "native", "libsrjt.so")

if not os.path.exists(_LIB):
    pytest.skip("libsrjt.so not built", allow_module_level=True)

from spark_rapids_jni_tpu import native as _native

lib = _native.load()   # single shared binding site (native/__init__.py)
assert lib is not None

INT32, STRING = 3, 24


def _np_ptr(a):
    return a.ctypes.data_as(C.c_void_p)


def _string_table(chars_per_row: int, n: int):
    """One int32 col + one string col with constant-length strings."""
    ints = np.arange(n, dtype=np.int32)
    offs = (np.arange(n + 1, dtype=np.int32) * chars_per_row)
    chars = np.full(offs[-1], ord("x"), dtype=np.uint8)
    h_int = lib.srjt_column_fixed(INT32, 0, n, _np_ptr(ints), None)
    h_str = lib.srjt_column_string(n, _np_ptr(offs), _np_ptr(chars), None)
    arr = (C.c_void_p * 2)(h_int, h_str)
    t = lib.srjt_table(arr, 2)
    lib.srjt_column_free(h_int)
    lib.srjt_column_free(h_str)
    return t


def test_oversized_row_fails_instead_of_hanging():
    lib.srjt_debug_set_max_batch_bytes(64)
    try:
        t = _string_table(chars_per_row=200, n=4)  # each row > 64B limit
        rows = lib.srjt_to_rows(t)
        assert not rows  # nullptr: conversion rejected, not an infinite loop
        lib.srjt_table_free(t)
    finally:
        lib.srjt_debug_set_max_batch_bytes(0)


def test_small_limit_still_batches_normal_rows():
    lib.srjt_debug_set_max_batch_bytes(256)
    try:
        t = _string_table(chars_per_row=8, n=64)
        rows = lib.srjt_to_rows(t)
        assert rows
        lib.srjt_rows_free(rows)
        lib.srjt_table_free(t)
    finally:
        lib.srjt_debug_set_max_batch_bytes(0)


def _import(data: np.ndarray, offsets: np.ndarray, n: int):
    return lib.srjt_rows_import(_np_ptr(data), len(data), _np_ptr(offsets), n)


def test_import_rejects_bad_offsets():
    data = np.zeros(64, dtype=np.uint8)
    # non-monotonic
    assert not _import(data, np.array([0, 40, 20, 64], dtype=np.int32), 3)
    # does not start at zero
    assert not _import(data, np.array([8, 32, 64], dtype=np.int32), 2)
    # does not end at data_size
    assert not _import(data, np.array([0, 32, 48], dtype=np.int32), 2)
    # negative
    assert not _import(data, np.array([0, -4, 64], dtype=np.int32), 2)
    # well-formed accepted
    h = _import(data, np.array([0, 32, 64], dtype=np.int32), 2)
    assert h
    lib.srjt_rows_free(h)


def _from_rows(rows_handle, type_ids):
    tids = np.asarray(type_ids, dtype=np.int32)
    return lib.srjt_from_rows(rows_handle, 0, _np_ptr(tids), None, len(tids))


def test_from_rows_rejects_short_rows():
    # schema int32+string: fixed area = 4(int)+4(pad)+8(slot)+1(validity)->24B
    data = np.zeros(16, dtype=np.uint8)  # one 16B row: too short
    h = _import(data, np.array([0, 16], dtype=np.int32), 1)
    assert h
    assert not _from_rows(h, [INT32, STRING])
    lib.srjt_rows_free(h)


def test_from_rows_rejects_out_of_row_string_slot():
    # Build a legitimate row, then corrupt the string slot to point past the
    # row's end (the shuffle-corruption case): must fail, not read OOB.
    t = _string_table(chars_per_row=8, n=1)
    rows = lib.srjt_to_rows(t)
    assert rows
    size = lib.srjt_rows_batch_size(rows, 0)
    buf = np.ctypeslib.as_array(lib.srjt_rows_batch_data(rows, 0),
                                shape=(size,)).copy()
    lib.srjt_rows_free(rows)
    lib.srjt_table_free(t)

    # round-trips clean before corruption
    offs = np.array([0, size], dtype=np.int32)
    h = _import(buf, offs, 1)
    back = _from_rows(h, [INT32, STRING])
    assert back
    lib.srjt_table_free(back)
    lib.srjt_rows_free(h)

    # The string (offset,len) slot lives at bytes 4..12 of the row for this
    # schema (int32 at 0, slot 4-aligned after it): offset at 4..8, length
    # at 8..12.  Corrupt the length to something huge:
    bad = buf.copy()
    bad[8:12] = np.frombuffer(np.int32(2**31 - 1).tobytes(), dtype=np.uint8)
    h = _import(bad, offs, 1)
    assert not _from_rows(h, [INT32, STRING])
    lib.srjt_rows_free(h)

    # corrupt the slot offset to point before the fixed area
    bad2 = buf.copy()
    bad2[4:8] = np.frombuffer(np.int32(2).tobytes(), dtype=np.uint8)
    h = _import(bad2, offs, 1)
    assert not _from_rows(h, [INT32, STRING])
    lib.srjt_rows_free(h)


def test_from_rows_rejects_overlapping_string_slots():
    """Two string columns whose slots both claim the same row tail must be
    rejected: JCUDF chars are concatenated in column order, so each slot's
    offset must equal the running cursor.  Overlap would let one crafted row
    amplify the chars allocation once per string column."""
    # schema: string + string → slots at 0..8 and 8..16, validity 16, fpv 17,
    # rows padded to 8 → 24B fixed area
    n = 1
    chars = np.frombuffer(b"abcdabcd", dtype=np.uint8).copy()
    offs = np.array([0, 4], dtype=np.int32)
    h1 = lib.srjt_column_string(n, _np_ptr(offs), _np_ptr(chars), None)
    offs2 = np.array([4, 8], dtype=np.int32) - 4
    h2 = lib.srjt_column_string(n, _np_ptr(offs2), _np_ptr(chars[4:].copy()),
                                None)
    arr = (C.c_void_p * 2)(h1, h2)
    t = lib.srjt_table(arr, 2)
    lib.srjt_column_free(h1)
    lib.srjt_column_free(h2)
    rows = lib.srjt_to_rows(t)
    assert rows
    size = lib.srjt_rows_batch_size(rows, 0)
    buf = np.ctypeslib.as_array(lib.srjt_rows_batch_data(rows, 0),
                                shape=(size,)).copy()
    lib.srjt_rows_free(rows)
    lib.srjt_table_free(t)

    offsets = np.array([0, size], dtype=np.int32)
    h = _import(buf, offsets, 1)
    back = _from_rows(h, [STRING, STRING])
    assert back                       # clean bytes round-trip
    lib.srjt_table_free(back)
    lib.srjt_rows_free(h)

    # make the SECOND slot's offset point back at the first column's chars
    bad = buf.copy()
    bad[8:12] = bad[0:4]              # slot2.offset := slot1.offset
    h = _import(bad, offsets, 1)
    assert not _from_rows(h, [STRING, STRING])
    lib.srjt_rows_free(h)
