"""TPC-H Q1 differential: exact-integer oracle for the decimal128 money
sums, pandas for the float statistics."""

import numpy as np
import pandas as pd
import pytest

from benchmarks import tpch_data
from spark_rapids_jni_tpu.models import tpch_q1
from spark_rapids_jni_tpu import types as T

CUTOFF = 10561 - 90   # 1998-12-01 minus ~90 days, in epoch days


@pytest.fixture(scope="module")
def data():
    return tpch_data.generate(n=20_000, seed=9)


@pytest.mark.slow
def test_q1_matches_exact_oracle(data):
    file_bytes, raw = data
    out = tpch_q1.run(file_bytes, CUTOFF)

    df = pd.DataFrame({k: v for k, v in raw.items()})
    df = df[df.ship <= CUTOFF]
    # exact integer oracle in unscaled units
    df["disc_price_u"] = df.price_c * (100 - df.disc_c)          # scale -4
    df["charge_u"] = df.disc_price_u * (100 + df.tax_c)          # scale -6
    g = (df.groupby(["flags", "status"])
         .agg(sum_qty=("qty", "sum"),
              sum_price_c=("price_c", "sum"),
              sum_disc_price_u=("disc_price_u", "sum"),
              sum_charge_u=("charge_u", "sum"),
              avg_qty=("qty", "mean"),
              avg_price_c=("price_c", "mean"),
              avg_disc_c=("disc_c", "mean"),
              cnt=("qty", "size"))
         .reset_index().sort_values(["flags", "status"]))

    assert out.num_rows == len(g)
    assert out[0].to_pylist() == g["flags"].tolist()
    assert out[1].to_pylist() == g["status"].tolist()
    assert out[2].to_pylist() == g.sum_qty.tolist()
    # decimal64 base-price sum keeps scale -2
    assert out[3].dtype == T.decimal64(-2)
    assert out[3].to_pylist() == g.sum_price_c.tolist()
    # decimal128 limb sums are EXACT integers at scales -4 / -6
    assert out[4].dtype == T.decimal128(-4)
    assert out[4].to_pylist() == g.sum_disc_price_u.tolist()
    assert out[5].dtype == T.decimal128(-6)
    assert out[5].to_pylist() == g.sum_charge_u.tolist()
    # float statistics (value domain: decimals carry their scale)
    np.testing.assert_allclose(out[6].to_numpy(),
                               g.avg_qty.to_numpy(), rtol=1e-12)
    np.testing.assert_allclose(out[7].to_numpy(),
                               g.avg_price_c.to_numpy() / 100.0, rtol=1e-12)
    np.testing.assert_allclose(out[8].to_numpy(),
                               g.avg_disc_c.to_numpy() / 100.0, rtol=1e-12)
    assert out[9].to_pylist() == g.cnt.tolist()


def test_q1_empty_after_cutoff(data):
    file_bytes, _ = data
    out = tpch_q1.run(file_bytes, -10**6)
    assert out.num_rows == 0
    # empty-path schema must match the populated path (incl. [0,2] lanes)
    assert out[3].dtype == T.decimal64(-2)
    assert out[4].dtype == T.decimal128(-4)
    assert out[4].data.shape == (0, 2)
    assert out[5].dtype == T.decimal128(-6)
