"""Whole-query compilation (models/compiled.py): every TPC-DS subset query
must trace into ONE jitted program under syncs capture/replay and produce
exactly the eager result — the per-query single-dispatch contract behind
the SF1 wall-time work (VERDICT r3 next-step #3)."""

import numpy as np
import pytest

from benchmarks import tpcds_data
from spark_rapids_jni_tpu.models import tpcds
from spark_rapids_jni_tpu.models.compiled import compile_query
from spark_rapids_jni_tpu.utils import syncs


@pytest.fixture(scope="module")
def tables():
    files = tpcds_data.generate(n_sales=20_000, n_items=300, seed=11)
    return tpcds.load_tables(files)


def _tables_equal(a, b):
    assert a.num_columns == b.num_columns
    assert a.num_rows == b.num_rows
    for i in range(a.num_columns):
        ca, cb = a[i], b[i]
        assert ca.dtype.id == cb.dtype.id
        if ca.dtype.id.name == "STRING":
            assert ca.to_pylist() == cb.to_pylist()
        elif ca.dtype.id.name in ("FLOAT32", "FLOAT64"):
            # integer/key/count results must match EXACTLY; float
            # aggregates may differ by reassociation ulps — fusing the
            # whole query lets XLA reshape reduction trees (observed: one
            # grand-total mean off by 1 ulp on the CPU backend).  The
            # tolerance is a few ulps of the dtype, so it actually absorbs
            # what the comment claims (1e-12 would not cover a single
            # float32 ulp at ~1.2e-7 relative).
            rtol = 1e-12 if ca.dtype.id.name == "FLOAT64" else 1e-6
            np.testing.assert_allclose(np.asarray(ca.to_numpy()),
                                       np.asarray(cb.to_numpy()),
                                       rtol=rtol, atol=0)
        else:
            np.testing.assert_array_equal(np.asarray(ca.to_numpy()),
                                          np.asarray(cb.to_numpy()))


# the three heaviest JIT compiles ride the slow lane; the other ~20
# cases keep capture/replay bit-identity inside the tier-1 time budget
_SLOW_COMPILE = {"q27_cube", "q19", "q36_rollup"}


@pytest.mark.parametrize(
    "qname", [pytest.param(q, marks=pytest.mark.slow)
              if q in _SLOW_COMPILE else q
              for q in sorted(tpcds.QUERIES)])
def test_compiled_matches_eager(tables, qname):
    qfn = tpcds.QUERIES[qname]
    cq = compile_query(qfn, tables)
    out = cq.run(tables)        # checked: validates the tape, then runs
    _tables_equal(out, cq.expected)
    # steady state: re-execution is ONE dispatch, ZERO host syncs
    before = syncs.sync_count()
    out2 = cq.run_unchecked(tables)
    assert syncs.sync_count() == before
    _tables_equal(out2, cq.expected)


@pytest.mark.slow
def test_stale_tape_raises(tables):
    """VERDICT r4 weak #6: re-running a compiled plan against refreshed
    data whose true resolved sizes differ (same shapes, different join
    cardinalities) must raise, not silently return wrong rows.  The
    reference re-measures its sizes every call (row_conversion.cu:
    2205-2215); run() re-measures on device with one stacked sync."""
    from spark_rapids_jni_tpu.models.compiled import StaleTapeError
    cq = compile_query(tpcds.QUERIES["q3"], tables)
    assert len(cq.tape) > 0
    # refreshed data: identical shapes, different content → different
    # join/filter cardinalities
    files2 = tpcds_data.generate(n_sales=20_000, n_items=300, seed=77)
    tables2 = tpcds.load_tables(files2)
    with pytest.raises(StaleTapeError):
        cq.run(tables2)
    # the same refreshed tables recompile cleanly
    cq2 = compile_query(tpcds.QUERIES["q3"], tables2)
    out = cq2.run(tables2)
    _tables_equal(out, cq2.expected)


@pytest.mark.slow
def test_replay_detects_divergence(tables):
    cq = compile_query(tpcds.QUERIES["q3"], tables)
    # a tape for a different plan must not silently misresolve
    with pytest.raises(Exception):
        with syncs.replay(list(cq.tape[:1])):
            tpcds.QUERIES["q3"](tables)
