"""ops.strings differential tests (pandas/python oracle).

Mirrors the reference's reliance on libcudf strings (SURVEY §2.9): the
operations here are the ones the Spark plugin needs for string sort keys,
string group-by keys, string equi-join keys, and TPC-DS-shaped predicates.
"""

import numpy as np
import pandas as pd
import pytest

import spark_rapids_jni_tpu as sr
from spark_rapids_jni_tpu import Column, Table, types as T
from spark_rapids_jni_tpu.ops import strings as S
from spark_rapids_jni_tpu.ops import (groupby_aggregate, inner_join,
                                      left_join, order_by, sort_table)

WORDS = ["", "a", "b", "aa", "ab", "spark", "tpu", "tpu-native", "Spark",
         "SPARK", "zz", "a\x00b", "a\x00", "longer string payload",
         "unicode ✓ bytes", "ab"]


def make_strings(n, seed=0, null_every=None):
    rng = np.random.default_rng(seed)
    vals = [WORDS[i] for i in rng.integers(0, len(WORDS), n)]
    if null_every:
        vals = [None if i % null_every == 0 else v
                for i, v in enumerate(vals)]
    return vals


# ---- dictionary encode ----------------------------------------------------

def test_dictionary_encode_roundtrip():
    vals = make_strings(97, seed=1)
    col = Column.strings_from_list(vals)
    codes, uniq = S.dictionary_encode(col)
    uniq_list = uniq.to_pylist()
    got = [uniq_list[c] for c in codes.data.tolist()]
    assert got == vals


def test_dictionary_encode_order_preserving():
    vals = make_strings(80, seed=2)
    col = Column.strings_from_list(vals)
    codes, _ = S.dictionary_encode(col)
    c = codes.data.tolist()
    for i in range(len(vals)):
        for j in range(i + 1, len(vals)):
            bi, bj = vals[i].encode(), vals[j].encode()
            if bi < bj:
                assert c[i] < c[j], (vals[i], vals[j])
            elif bi == bj:
                assert c[i] == c[j]
            else:
                assert c[i] > c[j]


def test_dictionary_encode_nulls_share_code():
    vals = make_strings(50, seed=3, null_every=7)
    col = Column.strings_from_list(vals)
    codes, _ = S.dictionary_encode(col)
    null_codes = {codes.data[i].item() for i, v in enumerate(vals) if v is None}
    assert len(null_codes) == 1
    assert codes.to_pylist() == [None if v is None else codes.data[i].item()
                                 for i, v in enumerate(vals)]


def test_dictionary_representative_prefers_valid_rows():
    """A masked-null row keeps its original bytes (mask_table semantics) and
    shares code 0 with the zeroed key; the dictionary entry for that code
    must come from a VALID empty-string row, never the null row's payload."""
    col = Column(
        sr.string,
        Column.strings_from_list(["xyz", ""]).data,
        Column.strings_from_list(["xyz", ""]).offsets,
        validity=np.asarray([False, True]))
    import jax.numpy as jnp
    col = Column(sr.string, col.data, col.offsets,
                 jnp.asarray([False, True]))
    codes, uniq = S.dictionary_encode(col)
    assert codes.data[0] == codes.data[1]      # both map to the zeroed key
    assert uniq.to_pylist()[int(codes.data[1])] == ""   # not "xyz"


def test_groupby_masked_null_string_key():
    """mask_table + groupby on a string key: the null group key must decode
    as null, and a real empty string must stay distinct from it."""
    from spark_rapids_jni_tpu.ops import mask_table
    t = Table([Column.strings_from_list(["xyz", "", "xyz", ""]),
               Column.from_numpy(np.asarray([1, 2, 4, 8], dtype=np.int64))])
    masked = mask_table(t, np.asarray([False, True, True, True]))
    out = groupby_aggregate(masked, [0], [(1, "sum")])
    rows = dict(zip(out[0].to_pylist(), out[1].to_numpy().tolist()))
    # the masked row's VALUE is null too, so the null group sums to 0
    assert rows == {None: 0, "": 10, "xyz": 4}


def test_encode_shared_cross_column_equality():
    a = Column.strings_from_list(["x", "y", "zz", "y"])
    b = Column.strings_from_list(["y", "zz", "nope", "x"])
    ca, cb = S.encode_shared([a, b])
    assert ca.data[1] == cb.data[0]       # "y" == "y"
    assert ca.data[2] == cb.data[1]       # "zz" == "zz"
    assert ca.data[0] == cb.data[3]       # "x" == "x"
    assert cb.data[2] not in ca.data.tolist()


# ---- sort -----------------------------------------------------------------

@pytest.mark.parametrize("null_every", [None, 5])
@pytest.mark.parametrize("asc", [True, False])
def test_string_sort_vs_python(asc, null_every):
    vals = make_strings(61, seed=4, null_every=null_every)
    t = Table([Column.strings_from_list(vals),
               Column.from_numpy(np.arange(61, dtype=np.int64))])
    out = sort_table(t, [0], ascending=[asc], nulls_first=[True])
    got = out[0].to_pylist()
    keyed = sorted([v for v in vals if v is not None],
                   key=lambda s: s.encode(), reverse=not asc)
    expect = [None] * (len(vals) - len(keyed)) + keyed
    assert got == expect


def test_string_secondary_key_sort():
    vals = ["b", "a", "b", "a", "c", "a"]
    nums = np.asarray([2, 3, 1, 1, 0, 2], dtype=np.int32)
    t = Table([Column.strings_from_list(vals), Column.from_numpy(nums)])
    out = sort_table(t, [0, 1])
    df = pd.DataFrame({"s": vals, "n": nums}).sort_values(["s", "n"])
    assert out[0].to_pylist() == df["s"].tolist()
    assert out[1].to_numpy().tolist() == df["n"].tolist()


# ---- groupby --------------------------------------------------------------

@pytest.mark.parametrize("null_every", [None, 6])
def test_groupby_string_key_vs_pandas(null_every):
    vals = make_strings(120, seed=5, null_every=null_every)
    rng = np.random.default_rng(5)
    nums = rng.integers(-100, 100, 120).astype(np.int64)
    t = Table([Column.strings_from_list(vals), Column.from_numpy(nums)])
    out = groupby_aggregate(t, [0], [(1, "sum"), (1, "count"), (1, "max")])

    # pure-Python oracle: pandas object-dtype groupby truncates keys at
    # embedded NUL bytes (C-string semantics in its hashtable), merging
    # 'a', 'a\x00', and 'a\x00b' into one group — WORDS includes exactly
    # those keys to pin the engine's full-bytes semantics
    groups: dict = {}
    for k, v in zip(vals, nums):
        groups.setdefault(k, []).append(int(v))
    exp_keys = sorted(groups, key=lambda k: (k is not None,
                                             b"" if k is None else k.encode()))
    assert out[0].to_pylist() == exp_keys
    np.testing.assert_array_equal(
        out[1].to_numpy(), [sum(groups[k]) for k in exp_keys])
    np.testing.assert_array_equal(
        out[2].to_numpy(), [len(groups[k]) for k in exp_keys])
    np.testing.assert_array_equal(
        out[3].to_numpy(), [max(groups[k]) for k in exp_keys])


# ---- join -----------------------------------------------------------------

def test_inner_join_string_key_vs_pandas():
    lk = ["a", "b", "c", "a", "d", "b"]
    rk = ["b", "a", "e", "b"]
    lt = Table([Column.strings_from_list(lk),
                Column.from_numpy(np.arange(6, dtype=np.int64))])
    rt = Table([Column.strings_from_list(rk),
                Column.from_numpy(np.arange(10, 14, dtype=np.int64))])
    out = inner_join(lt, rt, 0, 0)
    got = sorted(zip(out[1].to_numpy().tolist(), out[3].to_numpy().tolist()))
    ldf = pd.DataFrame({"k": lk, "lv": np.arange(6)})
    rdf = pd.DataFrame({"k": rk, "rv": np.arange(10, 14)})
    exp = sorted(zip(*ldf.merge(rdf, on="k")[["lv", "rv"]].T.values.tolist()))
    assert got == exp


def test_left_join_string_key_null_keys_never_match():
    lk = ["a", None, "c"]
    rk = ["a", None]
    lt = Table([Column.strings_from_list(lk),
                Column.from_numpy(np.arange(3, dtype=np.int32))])
    rt = Table([Column.strings_from_list(rk),
                Column.from_numpy(np.asarray([7, 8], dtype=np.int32))])
    out = left_join(lt, rt, 0, 0)
    rows = sorted(zip(out[1].to_pylist(), out[3].to_pylist()))
    assert rows == [(0, 7), (1, None), (2, None)]


# ---- equality / transforms ------------------------------------------------

def test_equal_to_and_scalar():
    a = Column.strings_from_list(["x", "yy", None, "z", ""])
    b = Column.strings_from_list(["x", "y", "q", None, ""])
    eq = S.equal_to(a, b)
    assert eq.to_pylist() == [True, False, None, None, True]
    eqs = S.equal_to_scalar(a, "x")
    assert eqs.to_pylist() == [True, False, None, False, False]


def test_upper_lower():
    vals = ["Spark", "TPU", "mixed Case 123", None, ""]
    col = Column.strings_from_list(vals)
    assert S.upper(col).to_pylist() == [
        None if v is None else v.upper() for v in vals]
    assert S.lower(col).to_pylist() == [
        None if v is None else v.lower() for v in vals]


@pytest.mark.parametrize("start,length", [(0, 3), (2, None), (1, 1), (5, 4)])
def test_substring(start, length):
    vals = ["hello", "ab", "", None, "longer payload"]
    col = Column.strings_from_list(vals)
    out = S.substring(col, start, length)
    expect = [None if v is None else
              (v[start:] if length is None else v[start:start + length])
              for v in vals]
    assert out.to_pylist() == expect


def test_concat():
    a = Column.strings_from_list(["x", "", None, "ab"])
    b = Column.strings_from_list(["1", "2", "3", None])
    out = S.concat(a, b)
    assert out.to_pylist() == ["x1", "2", None, None]


@pytest.mark.slow
def test_strings_roundtrip_through_rowconv():
    """String columns keyed ops compose with the JCUDF transcode."""
    vals = make_strings(40, seed=9, null_every=11)
    t = Table([Column.strings_from_list(vals),
               Column.from_numpy(np.arange(40, dtype=np.int64))])
    batches = sr.convert_to_rows(t)
    back = sr.convert_from_rows(batches[0], t.schema)
    assert back[0].to_pylist() == vals


class TestSearch:
    """contains/starts_with/ends_with/like vs a Python oracle."""

    def _col_and_vals(self, seed=0, n=300):
        import random
        rng = random.Random(seed)
        alphabet = "abcx_%"
        vals = [None if rng.random() < 0.1 else
                "".join(rng.choice(alphabet) for _ in range(rng.randrange(12)))
                for _ in range(n)]
        return Column.strings_from_list(vals), vals

    def test_contains(self):
        col, vals = self._col_and_vals()
        for pat in ["a", "ab", "abc", "xx", ""]:
            got = S.contains(col, pat).to_pylist()
            want = [None if v is None else (pat in v) for v in vals]
            assert got == want, pat

    def test_starts_ends(self):
        col, vals = self._col_and_vals(1)
        for pat in ["a", "ba", "ccc", ""]:
            assert (S.starts_with(col, pat).to_pylist()
                    == [None if v is None else v.startswith(pat)
                        for v in vals]), pat
            assert (S.ends_with(col, pat).to_pylist()
                    == [None if v is None else v.endswith(pat)
                        for v in vals]), pat

    def test_like_matches_python_regex(self):
        import re
        col, vals = self._col_and_vals(2)
        pats = ["a%", "%a", "%ab%", "a_c", "_", "%a%b%", "abc", "%", "",
                "a%b%c", "__%"]
        for pat in pats:
            rx = re.compile(
                "^" + "".join(".*" if ch == "%" else "." if ch == "_"
                              else re.escape(ch) for ch in pat) + "$",
                re.DOTALL)
            got = S.like(col, pat).to_pylist()
            want = [None if v is None else bool(rx.match(v)) for v in vals]
            assert got == want, (pat,
                                 [(v, g, w) for v, g, w in
                                  zip(vals, got, want) if g != w][:5])


class TestFormat:
    def test_format_int64_edges(self):
        vals = [0, 7, -7, 123456, -(2**63), 2**63 - 1, -1, 10**18, None]
        c = Column.from_numpy(
            np.asarray([0 if v is None else v for v in vals], np.int64),
            validity=np.asarray([v is not None for v in vals]))
        assert S.format_int64(c).to_pylist() == \
            [None if v is None else str(v) for v in vals]

    def test_format_int64_random_vs_python(self):
        rng = np.random.default_rng(0)
        v = rng.integers(-10**17, 10**17, 3000)
        got = S.format_int64(Column.from_numpy(v)).to_pylist()
        assert got == [str(x) for x in v.tolist()]

    def test_format_decimal(self):
        c = Column.from_numpy(np.asarray([12345, -5, 0, -12000], np.int64),
                              T.decimal64(-2))
        assert S.format_decimal(c).to_pylist() == \
            ["123.45", "-0.05", "0.00", "-120.00"]
        c2 = Column.from_numpy(np.asarray([45], np.int32), T.decimal32(2))
        assert S.format_decimal(c2).to_pylist() == ["4500"]

    def test_cast_string_roundtrip(self):
        from spark_rapids_jni_tpu.ops import cast
        vals = ["12345", "-7", None, "junk"]
        parsed = cast(Column.strings_from_list(vals), T.int64)
        assert parsed.to_pylist() == [12345, -7, None, None]
        back = cast(parsed, T.string)
        assert back.to_pylist() == ["12345", "-7", None, None]
        dec = cast(Column.strings_from_list(["1.25", "-3.5"]),
                   T.decimal64(-2))
        assert cast(dec, T.string).to_pylist() == ["1.25", "-3.50"]

    def test_cast_string_to_int32(self):
        from spark_rapids_jni_tpu.ops import cast
        out = cast(Column.strings_from_list(["42", "-1"]), T.int32)
        assert out.dtype == T.int32
        assert out.to_pylist() == [42, -1]

    def test_cast_string_to_date(self):
        from spark_rapids_jni_tpu.ops import cast
        out = cast(Column.strings_from_list(["1970-01-02", "bad"]),
                   T.timestamp_days)
        assert out.to_pylist() == [1, None]


class TestCastStringEdges:
    def test_bool_roundtrip(self):
        from spark_rapids_jni_tpu.ops import cast
        c = Column.strings_from_list(["true", "FALSE", " yes ", "0", "x",
                                      None])
        b = cast(c, T.bool8)
        assert b.to_pylist() == [True, False, True, False, None, None]
        back = cast(b, T.string)
        assert back.to_pylist() == ["true", "false", "true", "false",
                                    None, None]

    def test_date_roundtrip(self):
        from spark_rapids_jni_tpu.ops import cast
        days = np.asarray([0, 18321, -1, 2932896], np.int32)  # 9999-12-31
        d = Column.from_numpy(days, T.timestamp_days)
        s = cast(d, T.string)
        assert s.to_pylist() == ["1970-01-01", "2020-02-29", "1969-12-31",
                                 "9999-12-31"]
        back = cast(s, T.timestamp_days)
        np.testing.assert_array_equal(np.asarray(back.data), days)

    def test_string_to_narrow_int_overflow_null(self):
        from spark_rapids_jni_tpu.ops import cast
        c = Column.strings_from_list(["300", "42", "-129", "127"])
        out = cast(c, T.int8)
        assert out.to_pylist() == [None, 42, None, 127]

    def test_string_to_decimal32_overflow_null(self):
        from spark_rapids_jni_tpu.ops import cast
        c = Column.strings_from_list(["9999999999", "12.5"])
        out = cast(c, T.decimal32(-1))
        assert out.to_pylist() == [None, 125]

    def test_timestamp_us_to_string_rejected(self):
        from spark_rapids_jni_tpu.ops import cast
        c = Column.from_numpy(np.asarray([0], np.int64), T.timestamp_us)
        with pytest.raises(NotImplementedError):
            cast(c, T.string)
        with pytest.raises(NotImplementedError):
            cast(Column.strings_from_list(["1"]), T.timestamp_us)


class TestFormatUnsignedAndDecimalEdges:
    def test_uint64_above_2_63(self):
        from spark_rapids_jni_tpu.ops import cast
        c = Column.from_numpy(np.asarray([2**63, 2**64 - 1, 0], np.uint64))
        assert cast(c, T.string).to_pylist() == \
            ["9223372036854775808", "18446744073709551615", "0"]

    def test_string_to_uint64(self):
        from spark_rapids_jni_tpu.ops import cast
        out = cast(Column.strings_from_list(["5", "-1", "42"]), T.uint64)
        assert out.to_pylist() == [5, None, 42]

    def test_decimal_int64_min(self):
        c = Column.from_numpy(np.asarray([-(2**63)], np.int64),
                              T.decimal64(-2))
        assert S.format_decimal(c).to_pylist() == ["-92233720368547758.08"]

    def test_decimal_positive_scale_no_wrap(self):
        c = Column.from_numpy(np.asarray([10**18, -3], np.int64),
                              T.decimal64(2))
        assert S.format_decimal(c).to_pylist() == [str(10**20), "-300"]

    def test_decimal_positive_scale_zero(self):
        c = Column.from_numpy(np.asarray([0, 3], np.int64), T.decimal64(2))
        assert S.format_decimal(c).to_pylist() == ["0", "300"]
