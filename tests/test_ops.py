"""Columnar op library tests, differential against pandas (independent oracle)."""

import numpy as np
import pandas as pd
import pytest
import jax.numpy as jnp

import spark_rapids_jni_tpu as sr
from spark_rapids_jni_tpu import Column, Table
from spark_rapids_jni_tpu import ops

RNG = np.random.default_rng(5)


def int_col(vals, validity=None, dt=None):
    return Column.from_numpy(np.asarray(vals), dt, validity)


# ---- cast -----------------------------------------------------------------

def test_cast_numeric_widening_and_narrowing():
    c = int_col(np.asarray([1, -2, 300], dtype=np.int32))
    assert ops.cast(c, sr.int64).to_pylist() == [1, -2, 300]
    assert ops.cast(c, sr.float32).data.dtype == np.float32
    assert ops.cast(c, sr.int8).to_pylist() == [1, -2, 44]  # 300 wraps


def test_cast_bool():
    c = int_col(np.asarray([0, 3, -1], dtype=np.int32))
    assert ops.cast(c, sr.bool8).to_pylist() == [False, True, True]
    b = ops.cast(c, sr.bool8)
    assert ops.cast(b, sr.int64).to_pylist() == [0, 1, 1]


def test_cast_decimal_rescale_round_half_away():
    # decimal(-2) value 12.345 stored as 1234.5? no: unscaled*10^-2
    c = Column.from_numpy(np.asarray([1234, -1234, 1250, -1250, 1249],
                                     dtype=np.int64), sr.decimal64(-3))
    # rescale -3 → -2: divide by 10, round half away from zero
    out = ops.cast(c, sr.decimal64(-2))
    assert out.to_pylist() == [123, -123, 125, -125, 125]


def test_cast_decimal_to_float_and_back():
    c = Column.from_numpy(np.asarray([12345, -500], dtype=np.int64),
                          sr.decimal64(-2))
    f = ops.cast(c, sr.float64)
    np.testing.assert_allclose(f.to_numpy(), [123.45, -5.0])
    back = ops.cast(f, sr.decimal64(-2))
    assert back.to_pylist() == [12345, -500]


# ---- filter ---------------------------------------------------------------

def test_apply_boolean_mask_fixed_and_string():
    t = Table.from_pydict({"a": [1, 2, 3, 4], "s": ["w", "x", "y", "z"]})
    out = ops.apply_boolean_mask(t, jnp.asarray([True, False, True, False]))
    assert out[0].to_pylist() == [1, 3]
    assert out[1].to_pylist() == ["w", "y"]


def test_mask_table_matches_compacting_filter_for_aggs():
    vals = RNG.integers(0, 100, 1000, dtype=np.int64)
    mask = RNG.random(1000) < 0.5
    t = Table([int_col(vals)])
    compacted = ops.apply_boolean_mask(t, jnp.asarray(mask))
    masked = ops.mask_table(t, jnp.asarray(mask))
    assert int(ops.sum_(compacted[0])) == int(ops.sum_(masked[0]))
    assert int(ops.valid_count(masked[0])) == mask.sum()


# ---- reductions -----------------------------------------------------------

def test_reductions_null_aware():
    c = int_col(np.asarray([5, 100, -7, 3], dtype=np.int64),
                validity=np.asarray([True, False, True, True]))
    assert int(ops.sum_(c)) == 1
    assert int(ops.min_(c)) == -7
    assert int(ops.max_(c)) == 5
    assert int(ops.valid_count(c)) == 3
    np.testing.assert_allclose(float(ops.mean(c)), 1 / 3)


# ---- sort -----------------------------------------------------------------

def test_sort_multi_key_vs_pandas():
    n = 500
    a = RNG.integers(0, 10, n, dtype=np.int64)
    b = RNG.standard_normal(n).astype(np.float32)
    t = Table([int_col(a), Column.from_numpy(b)])
    out = ops.sort_table(t, keys=[0, 1])
    df = pd.DataFrame({"a": a, "b": b}).sort_values(["a", "b"],
                                                    kind="stable")
    np.testing.assert_array_equal(out[0].to_numpy(), df["a"].to_numpy())
    np.testing.assert_array_equal(out[1].to_numpy(), df["b"].to_numpy())


def test_sort_descending_and_nulls():
    c = int_col(np.asarray([3, 1, 2, 9], dtype=np.int64),
                validity=np.asarray([True, True, True, False]))
    out = ops.sort_table(Table([c]), keys=[0], ascending=[False],
                         nulls_first=[False])
    assert out[0].to_pylist() == [3, 2, 1, None]
    out = ops.sort_table(Table([c]), keys=[0], ascending=[True],
                         nulls_first=[True])
    assert out[0].to_pylist() == [None, 1, 2, 3]


# ---- groupby --------------------------------------------------------------

def test_groupby_vs_pandas():
    n = 2000
    k = RNG.integers(0, 37, n, dtype=np.int64)
    v = RNG.integers(-50, 50, n, dtype=np.int64)
    f = RNG.standard_normal(n).astype(np.float64)
    t = Table([int_col(k), int_col(v), Column.from_numpy(f)])
    out = ops.groupby_aggregate(t, [0], [(1, "sum"), (1, "count"),
                                         (1, "min"), (1, "max"), (2, "mean")])
    df = pd.DataFrame({"k": k, "v": v, "f": f}).groupby("k").agg(
        s=("v", "sum"), c=("v", "count"), mn=("v", "min"), mx=("v", "max"),
        fm=("f", "mean")).reset_index().sort_values("k")
    np.testing.assert_array_equal(out[0].to_numpy(), df["k"].to_numpy())
    np.testing.assert_array_equal(out[1].to_numpy(), df["s"].to_numpy())
    np.testing.assert_array_equal(out[2].to_numpy(), df["c"].to_numpy())
    np.testing.assert_array_equal(out[3].to_numpy(), df["mn"].to_numpy())
    np.testing.assert_array_equal(out[4].to_numpy(), df["mx"].to_numpy())
    np.testing.assert_allclose(out[5].to_numpy(), df["fm"].to_numpy())


def test_groupby_multi_key_and_nulls():
    k1 = np.asarray([1, 1, 2, 2, 1], dtype=np.int64)
    k2 = np.asarray([0, 0, 0, 1, 0], dtype=np.int32)
    v = np.asarray([10, 20, 30, 40, 99], dtype=np.int64)
    vv = np.asarray([True, True, True, True, False])
    t = Table([int_col(k1), int_col(k2), int_col(v, validity=vv)])
    out = ops.groupby_aggregate(t, [0, 1], [(2, "sum"), (2, "count")])
    # groups: (1,0)->sum 30 count 2 (null 99 skipped), (2,0)->30, (2,1)->40
    assert out[0].to_pylist() == [1, 2, 2]
    assert out[1].to_pylist() == [0, 0, 1]
    assert out[2].to_pylist() == [30, 30, 40]
    assert out[3].to_pylist() == [2, 1, 1]


def test_groupby_min_of_all_null_group_is_null():
    k = np.asarray([1, 1, 2], dtype=np.int64)
    v = np.asarray([7, 8, 9], dtype=np.int64)
    valid = np.asarray([False, False, True])
    t = Table([int_col(k), int_col(v, validity=valid)])
    out = ops.groupby_aggregate(t, [0], [(1, "min")])
    assert out[1].to_pylist() == [None, 9]


# ---- joins ----------------------------------------------------------------

def test_inner_join_vs_pandas():
    nl, nr = 300, 200
    lk = RNG.integers(0, 50, nl, dtype=np.int64)
    rk = RNG.integers(0, 50, nr, dtype=np.int64)
    lv = np.arange(nl, dtype=np.int32)
    rv = np.arange(nr, dtype=np.int32) + 1000
    lt = Table([int_col(lk), int_col(lv)])
    rt = Table([int_col(rk), int_col(rv)])
    out = ops.inner_join(lt, rt, 0, 0)
    got = sorted(zip(out[0].to_pylist(), out[1].to_pylist(),
                     out[3].to_pylist()))
    df = pd.merge(pd.DataFrame({"k": lk, "lv": lv}),
                  pd.DataFrame({"k": rk, "rv": rv}), on="k")
    expect = sorted(zip(df["k"], df["lv"], df["rv"]))
    assert got == expect


def test_left_join_nulls_unmatched():
    lt = Table([int_col(np.asarray([1, 2, 3], dtype=np.int64)),
                int_col(np.asarray([10, 20, 30], dtype=np.int32))])
    rt = Table([int_col(np.asarray([2, 2], dtype=np.int64)),
                int_col(np.asarray([7, 8], dtype=np.int32))])
    out = ops.left_join(lt, rt, 0, 0)
    rows = sorted(zip(out[0].to_pylist(), out[3].to_pylist(),
                      key := [0] * out.num_rows))
    ks = out[0].to_pylist()
    rvs = out[3].to_pylist()
    assert sorted(zip(ks, [r if r is not None else -1 for r in rvs])) == \
        [(1, -1), (2, 7), (2, 8), (3, -1)]


def test_semi_anti_join():
    lt = Table([int_col(np.asarray([1, 2, 3, 4], dtype=np.int64))])
    rt = Table([int_col(np.asarray([2, 4, 4], dtype=np.int64))])
    assert ops.semi_join(lt, rt, 0, 0)[0].to_pylist() == [2, 4]
    assert ops.anti_join(lt, rt, 0, 0)[0].to_pylist() == [1, 3]


def test_join_null_keys_never_match():
    lt = Table([int_col(np.asarray([1, 2], dtype=np.int64),
                        validity=np.asarray([True, False]))])
    rt = Table([int_col(np.asarray([2, 1], dtype=np.int64),
                        validity=np.asarray([False, True]))])
    out = ops.inner_join(lt, rt, 0, 0)
    assert out[0].to_pylist() == [1]


def test_join_empty_right():
    lt = Table([int_col(np.asarray([1, 2], dtype=np.int64))])
    rt = Table([int_col(np.zeros(0, dtype=np.int64))])
    assert ops.inner_join(lt, rt, 0, 0).num_rows == 0
    out = ops.left_join(lt, rt, 0, 0)
    assert out[0].to_pylist() == [1, 2]
    assert out[1].to_pylist() == [None, None]


# ---- Spark float ordering: NaN is the largest value -----------------------

def test_sort_float_nan_ordering():
    import numpy as np
    from spark_rapids_jni_tpu import Column, Table
    from spark_rapids_jni_tpu.ops import sort_table
    vals = np.asarray([1.5, np.nan, -2.0, 0.0, np.nan, 7.25],
                      dtype=np.float32)
    t = Table([Column.from_numpy(vals)])
    asc = sort_table(t, [0])[0].to_numpy()
    # ascending: NaN last (Spark: NaN > everything)
    assert np.isnan(asc[-2:]).all() and not np.isnan(asc[:-2]).any()
    np.testing.assert_array_equal(asc[:-2], np.sort(vals[~np.isnan(vals)]))
    desc = sort_table(t, [0], ascending=[False])[0].to_numpy()
    # descending: NaN first
    assert np.isnan(desc[:2]).all() and not np.isnan(desc[2:]).any()
    np.testing.assert_array_equal(
        desc[2:], np.sort(vals[~np.isnan(vals)])[::-1])


def test_sort_negative_zero_equal():
    import numpy as np
    from spark_rapids_jni_tpu import Column, Table
    from spark_rapids_jni_tpu.ops import sort_table
    vals = np.asarray([-0.0, 1.0, 0.0, -1.0], dtype=np.float64)
    for asc in (True, False):
        got = sort_table(Table([Column.from_numpy(vals)]), [0],
                         ascending=[asc])[0].to_numpy()
        expect = np.sort(vals) if asc else np.sort(vals)[::-1]
        np.testing.assert_array_equal(np.sign(got) + got, np.sign(expect) + expect)


class TestOuterJoins:
    def _tables(self):
        left = Table([Column.from_numpy(np.asarray([1, 2, 3, 2], np.int64)),
                      Column.strings_from_list(["a", "b", "c", "d"])])
        right = Table([Column.from_numpy(np.asarray([2, 4], np.int64)),
                       Column.from_numpy(np.asarray([20, 40], np.int32))])
        return left, right

    def test_full_outer_matches_pandas(self):
        import pandas as pd
        left, right = self._tables()
        out = ops.full_outer_join(left, right, 0, 2 - 2)
        ldf = pd.DataFrame({"k": [1, 2, 3, 2], "s": ["a", "b", "c", "d"]})
        rdf = pd.DataFrame({"k2": [2, 4], "v": [20, 40]})
        exp = ldf.merge(rdf, left_on="k", right_on="k2", how="outer")
        assert out.num_rows == len(exp)
        got = sorted(zip(out[0].to_pylist(), out[1].to_pylist(),
                         out[2].to_pylist(), out[3].to_pylist()),
                     key=lambda r: (r[0] is None, r[0], r[3] or 0))
        want = sorted(
            [(None if pd.isna(r.k) else int(r.k),
              None if pd.isna(r.s) else r.s,
              None if pd.isna(r.k2) else int(r.k2),
              None if pd.isna(r.v) else int(r.v))
             for r in exp.itertuples()],
            key=lambda r: (r[0] is None, r[0] if r[0] is not None else 0,
                           r[3] or 0))
        assert sorted(map(repr, got)) == sorted(map(repr, want))

    def test_right_join(self):
        left, right = self._tables()
        out = ops.right_join(left, right, 0, 0)
        # rows: key2 matched twice (b, d), key4 unmatched
        rows = set(zip(out[0].to_pylist(), out[1].to_pylist(),
                       out[2].to_pylist(), out[3].to_pylist()))
        assert rows == {(2, "b", 2, 20), (2, "d", 2, 20),
                        (None, None, 4, 40)}

    def test_full_outer_all_matched_is_left_join(self):
        left = Table([Column.from_numpy(np.asarray([1, 2], np.int64))])
        right = Table([Column.from_numpy(np.asarray([1, 2], np.int64))])
        out = ops.full_outer_join(left, right, 0, 0)
        assert out.num_rows == 2


class TestGroupbyVarStd:
    def test_var_std_match_pandas(self):
        import pandas as pd
        rng = np.random.default_rng(5)
        keys = rng.integers(0, 5, 200).astype(np.int32)
        vals = rng.standard_normal(200)
        valid = rng.random(200) < 0.8
        t = Table([Column.from_numpy(keys),
                   Column.from_numpy(vals, validity=valid)])
        out = ops.groupby_aggregate(t, [0], [(1, "var"), (1, "std")])
        df = pd.DataFrame({"k": keys, "v": np.where(valid, vals, np.nan)})
        exp = df.groupby("k")["v"].agg(["var", "std"]).reset_index()
        np.testing.assert_allclose(out[1].to_numpy(),
                                   exp["var"].to_numpy(), rtol=1e-9)
        np.testing.assert_allclose(out[2].to_numpy(),
                                   exp["std"].to_numpy(), rtol=1e-9)

    def test_var_single_row_group_is_null(self):
        t = Table([Column.from_numpy(np.asarray([1, 2, 2], np.int32)),
                   Column.from_numpy(np.asarray([5.0, 1.0, 3.0]))])
        out = ops.groupby_aggregate(t, [0], [(1, "var")])
        assert out[1].to_pylist() == [None, 2.0]


class TestGroupbyNullKeys:
    def test_masked_rows_form_one_null_group(self):
        # mask_table keeps stale payloads under nulls: they must still
        # collapse into ONE null group (Spark GROUP BY null semantics)
        t = Table([Column.from_numpy(np.asarray([5, 7, 1], np.int64)),
                   Column.from_numpy(np.asarray([10, 20, 30], np.int64))])
        masked = ops.mask_table(t, jnp.asarray([False, False, True]))
        out = ops.groupby_aggregate(masked, [0], [(1, "count")])
        assert out.num_rows == 2   # {null, 1}

    def test_multi_key_null_group_not_split_by_stale_payload(self):
        # null keys must tie in the sort so the secondary key orders them;
        # otherwise the raw payload under the mask splits the null group
        # into one segment per (stale value, b) run
        rng = np.random.default_rng(13)
        n = 1500
        a = rng.integers(0, 7, n).astype(np.int64)
        b = rng.integers(0, 5, n).astype(np.int64)
        v = rng.integers(-100, 100, n).astype(np.int64)
        av = rng.random(n) < 0.9
        t = Table([Column.from_numpy(a, validity=av),
                   Column.from_numpy(b), Column.from_numpy(v)])
        out = ops.groupby_aggregate(t, [0, 1], [(2, "sum")])
        df = pd.DataFrame({"a": np.where(av, a, -1), "b": b, "v": v})
        want = df.groupby(["a", "b"])["v"].sum()
        got = sorted(zip([-1 if k is None else k
                          for k in out[0].to_pylist()],
                         out[1].to_pylist(), out[2].to_pylist()))
        assert got == sorted((ka, kb, s) for (ka, kb), s in want.items())

    def test_var_numerically_stable(self):
        # mean >> spread: the naive sum-of-squares identity returns 0.0
        vals = np.asarray([1e8, 1e8 + 1, 1e8 + 2], np.float64)
        t = Table([Column.from_numpy(np.ones(3, np.int32)),
                   Column.from_numpy(vals)])
        out = ops.groupby_aggregate(t, [0], [(1, "var")])
        np.testing.assert_allclose(out[1].to_numpy(), [1.0], rtol=1e-9)


class TestDecimalStatistics:
    def test_groupby_var_mean_decimal_scaled(self):
        # var/mean over decimal64(-2) must be in VALUE domain, not cents
        t = Table([Column.from_numpy(np.ones(2, np.int32)),
                   Column.from_numpy(np.asarray([100, 300], np.int64),
                                     sr.decimal64(-2))])
        out = ops.groupby_aggregate(t, [0], [(1, "var"), (1, "mean")])
        np.testing.assert_allclose(out[1].to_numpy(), [2.0])
        np.testing.assert_allclose(out[2].to_numpy(), [2.0])


class TestFirstLastNunique:
    def test_first_last_match_pandas(self):
        rng = np.random.default_rng(6)
        k = rng.integers(0, 8, 300).astype(np.int32)
        v = rng.integers(-50, 50, 300).astype(np.int64)
        valid = rng.random(300) < 0.8
        t = Table([Column.from_numpy(k),
                   Column.from_numpy(v, validity=valid)])
        out = ops.groupby_aggregate(t, [0], [(1, "first"), (1, "last")])
        df = pd.DataFrame({"k": k, "v": np.where(valid, v.astype(float),
                                                 np.nan)})
        exp = (df.groupby("k")["v"].agg(["first", "last"])
               .reset_index().sort_values("k"))
        # note: groupby sorts rows by key (stable), so "first" is the first
        # valid value in ORIGINAL order within the group — pandas agrees
        assert out[1].to_pylist() == \
            [None if pd.isna(x) else int(x) for x in exp["first"]]
        assert out[2].to_pylist() == \
            [None if pd.isna(x) else int(x) for x in exp["last"]]

    def test_nunique_matches_pandas(self):
        rng = np.random.default_rng(7)
        k = rng.integers(0, 5, 200).astype(np.int32)
        s = [None if rng.random() < 0.1 else f"v{rng.integers(0, 7)}"
             for _ in range(200)]
        t = Table([Column.from_numpy(k), Column.strings_from_list(s)])
        out = ops.groupby_nunique(t, [0], 1)
        df = pd.DataFrame({"k": k, "s": s})
        exp = (df.groupby("k")["s"].nunique().reset_index()
               .sort_values("k"))
        assert out[0].to_pylist() == exp["k"].tolist()
        assert out[1].to_pylist() == exp["s"].tolist()

    def test_string_value_agg_rejected_count_allowed(self):
        t = Table([Column.from_numpy(np.asarray([1, 1], np.int32)),
                   Column.strings_from_list(["a", None])])
        out = ops.groupby_aggregate(t, [0], [(1, "count")])
        assert out[1].to_pylist() == [1]
        with pytest.raises(NotImplementedError):
            ops.groupby_aggregate(t, [0], [(1, "first")])


class TestFloat64BitStorage:
    """FLOAT64 columns store u32 [n,2] bit pairs (round-3 invariant) —
    Spark-semantics regressions found in the round-3 review."""

    def test_groupby_negzero_and_nan_keys_collapse(self):
        # Spark grouping: -0.0 == 0.0 and all NaNs are one group
        keys = np.asarray([0.0, -0.0, np.nan, np.nan, 1.0], np.float64)
        t = Table([Column.from_numpy(keys),
                   Column.from_numpy(np.ones(5, np.int64))])
        out = ops.groupby_aggregate(t, [0], [(1, "count")])
        assert out.num_rows == 3  # {0.0, 1.0, NaN}
        counts = sorted(out[1].to_pylist())
        assert counts == [1, 2, 2]

    def test_sort_negative_nan_is_largest(self):
        neg_nan = np.frombuffer(
            np.uint64(0xFFF8000000000001).tobytes(), np.float64)[0]
        vals = np.asarray([1.0, neg_nan, -np.inf, np.inf, -1.0], np.float64)
        t = Table([Column.from_numpy(vals)])
        asc = ops.sort_table(t, [0])[0].to_numpy()
        assert np.isnan(asc[-1]) and asc[0] == -np.inf
        desc = ops.sort_table(t, [0], ascending=[False])[0].to_numpy()
        assert np.isnan(desc[0]) and desc[-1] == -np.inf

    def test_scan_result_respects_invariant(self):
        col = Column.from_numpy(np.asarray([1.5, 2.5, 3.0], np.float64))
        out = ops.cumulative_sum(Table([col])[0])
        assert out.data.ndim == 2 and str(out.data.dtype) == "uint32"
        np.testing.assert_allclose(out.to_numpy(), [1.5, 4.0, 7.0])

    def test_native_pack_f64_bytes_exact(self):
        from spark_rapids_jni_tpu.rowconv import native as cpp, reference as ref
        if not cpp.available():
            import pytest
            pytest.skip("native engine unavailable")
        t = Table([Column.from_numpy(np.asarray([1.5, -0.0, 3e300]))])
        cb, co = cpp.to_rows_np(t)
        ob, oo = ref.to_rows_np(t)
        np.testing.assert_array_equal(cb, ob)
