"""Serving-runtime (exec/) tests: differential, backpressure, caches.

The exec subsystem's contract is that concurrency, admission
degradation, plan caching, and prefetch change LATENCY, never results —
plus a typed failure surface (queue-full, deadline, shutdown,
quarantine) instead of stalls.  These tests hold all of it:

* concurrent differential — TPC-DS queries served by a 4-worker
  scheduler, submitted from 4 client threads, bit-identical to serial
  eager execution; repeated with the HBM arena + a tiny build-index
  cache so eviction races run under real concurrency.
* typed backpressure/timeout — ``ExecQueueFull`` at queue depth,
  ``ExecDeadlineExceeded`` for queued-past-deadline requests,
  ``ExecShutdown`` for drained requests, quarantine fail-fast.
* plan cache — hit/miss/eviction/expiry counters, single-flight
  compilation, degraded-variant key separation.
* admission — deferred under a mid cap, degraded (sorted engine) under
  a tiny cap with parity against the dense serial run.
* thread-safety regressions — the races fixed alongside this subsystem:
  prefetch stage/take, ``SpillableArrays`` concurrent fault-back,
  ``WeakIdMemo`` capped put storm, thread-local ``syncs`` capture.
"""

import gc
import os
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spark_rapids_jni_tpu import exec as xc
from spark_rapids_jni_tpu import types as T
from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.utils import metrics


@pytest.fixture(autouse=True)
def _metrics_on():
    metrics.set_enabled(True)
    metrics.reset()
    yield
    metrics.reset()
    metrics.set_enabled(None)


def _mkcol(vals):
    return Column(T.DType(T.TypeId.INT32),
                  jnp.asarray(np.asarray(vals, np.int32)))


def _mktab(n, seed):
    rng = np.random.default_rng(seed)
    return Table([_mkcol(rng.integers(0, 100, n)),
                  _mkcol(rng.integers(0, 7, n))])


def _q_sum(tbls):
    t = tbls["t"]
    return Table([Column(T.DType(T.TypeId.INT64),
                         jnp.sum(t.columns[0].data.astype(jnp.int64))
                         .reshape(1))])


def _canon(result):
    return [np.asarray(l) for l in jax.tree_util.tree_leaves(result)]


def _same(a, b):
    return len(a) == len(b) and all(
        x.shape == y.shape and np.array_equal(x, y)
        for x, y in zip(a, b))


# --- TPC-DS differential -----------------------------------------------------


QNAMES = ["q3", "q42", "q55"]


@pytest.fixture(scope="module")
def tpcds_tables():
    # same parameters as test_compiled_query's dataset: generate() is
    # memoized, so this module rides that module's decoded tables
    # instead of paying a second cold scan
    from benchmarks import tpcds_data
    from spark_rapids_jni_tpu.models import tpcds
    files = tpcds_data.generate(n_sales=20_000, n_items=300, seed=11)
    return tpcds.load_tables(files)


@pytest.fixture(scope="module")
def tpcds_oracle(tpcds_tables):
    from spark_rapids_jni_tpu.models import tpcds
    return {q: _canon(tpcds.QUERIES[q](tpcds_tables)) for q in QNAMES}


def _serve_mix(tables, oracle, **sched_kw):
    """Submit each query 4x from 4 client threads; return mismatch count
    and the tickets."""
    from spark_rapids_jni_tpu.models import tpcds
    mix = [(i, q) for i in range(4) for q in QNAMES]
    tickets = {}
    errs = []
    with xc.QueryScheduler(workers=4, **sched_kw) as sched:
        def client(i):
            try:
                for j, q in mix:
                    if j == i:
                        tickets[(i, q)] = sched.submit(
                            q, tpcds.QUERIES[q], tables)
            except Exception as e:       # surfaced to the test
                errs.append(e)
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errs, errs
        bad = sum(not _same(_canon(tk.result(timeout=300)), oracle[q])
                  for (_, q), tk in tickets.items())
    return bad, list(tickets.values())


def test_concurrent_differential(tpcds_tables, tpcds_oracle):
    bad, _ = _serve_mix(tpcds_tables, tpcds_oracle)
    assert bad == 0
    snap = metrics.snapshot()["counters"]
    assert snap.get("exec.completed", 0) == 12
    # 3 distinct (query, fingerprint) keys; the other 9 requests hit
    assert snap.get("exec.plan_cache.miss", 0) == 3
    assert snap.get("exec.plan_cache.hit", 0) == 9


def test_concurrent_differential_arena_evictions(tpcds_tables, tpcds_oracle):
    """Same differential with the arena on and a build-index cache so
    small every concurrent join evicts its neighbor — the eviction-race
    surface (shared budget lock, spill registry) under real load."""
    from spark_rapids_jni_tpu.memory import budget, spill
    from spark_rapids_jni_tpu.models import tpcds
    from spark_rapids_jni_tpu.ops import join_plan
    oracle = tpcds_oracle
    saved = {k: os.environ.get(k)
             for k in ("SRJT_HBM_ARENA", "SRJT_INDEX_CACHE_CAP")}
    os.environ["SRJT_HBM_ARENA"] = "1"
    os.environ["SRJT_INDEX_CACHE_CAP"] = "4k"
    budget.set_enabled(None)
    join_plan._INDEX_CACHE.clear()
    spill.reset()
    budget.reset()
    try:
        # eager (compiled=False): the index cache is live only outside
        # capture/replay, so eager serving is what races on it
        from functools import partial
        mix = [(i, q) for i in range(4) for q in QNAMES]
        tickets = []
        with xc.QueryScheduler(workers=4) as sched:
            for _, q in mix:
                tickets.append(
                    (q, sched.submit(q, tpcds.QUERIES[q], tpcds_tables,
                                     compiled=False)))
            bad = sum(not _same(_canon(tk.result(timeout=300)), oracle[q])
                      for q, tk in tickets)
        assert bad == 0
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        budget.set_enabled(None)
        join_plan._INDEX_CACHE.clear()
        spill.reset()
        budget.reset()


def test_degraded_admission_parity(tpcds_tables, tpcds_oracle):
    """A cap every request exceeds: all requests degrade to the sorted
    engine, complete, and match the dense serial oracle bit-for-bit."""
    from spark_rapids_jni_tpu.models import tpcds
    oracle = tpcds_oracle
    tickets = []
    with xc.QueryScheduler(workers=2, inflight_bytes=4096) as sched:
        for q in QNAMES:
            tickets.append((q, sched.submit(q, tpcds.QUERIES[q],
                                            tpcds_tables, compiled=False)))
        for q, tk in tickets:
            assert _same(_canon(tk.result(timeout=300)), oracle[q]), q
            assert tk.degraded
    snap = metrics.snapshot()["counters"]
    assert snap.get("exec.admission.degraded", 0) >= 3
    assert snap.get("exec.failed", 0) == 0


# --- backpressure / deadlines / lifecycle ------------------------------------


def _q_slow(tbls):
    time.sleep(0.1)
    return _q_sum(tbls)


def test_queue_full_typed():
    tables = {"t": _mktab(100, 0)}
    with xc.QueryScheduler(workers=1, queue_depth=2) as sched:
        held, full = [], 0
        for _ in range(10):
            try:
                held.append(sched.submit("s", _q_slow, tables,
                                         compiled=False))
            except xc.ExecQueueFull as e:
                full += 1
                assert e.depth == 2
        assert full >= 1
        for tk in held:
            tk.result(timeout=60)
    assert metrics.snapshot()["counters"].get("exec.queue.rejected") == full


def test_deadline_in_queue_typed():
    tables = {"t": _mktab(100, 0)}
    with xc.QueryScheduler(workers=1, queue_depth=4) as sched:
        blocker = sched.submit("s", _q_slow, tables, compiled=False)
        tk = sched.submit("dl", _q_slow, tables, compiled=False,
                          timeout_s=0.001)
        with pytest.raises(xc.ExecDeadlineExceeded) as ei:
            tk.result(timeout=60)
        assert ei.value.stage == "queue"
        blocker.result(timeout=60)


def test_shutdown_drains_typed():
    tables = {"t": _mktab(100, 0)}
    sched = xc.QueryScheduler(workers=1, queue_depth=8)
    held = [sched.submit("s", _q_slow, tables, compiled=False)
            for _ in range(5)]
    sched.shutdown(wait=True)
    outcomes = []
    for tk in held:
        try:
            tk.result(timeout=10)
            outcomes.append("ok")
        except xc.ExecShutdown:
            outcomes.append("shutdown")
    assert "shutdown" in outcomes          # queued requests drained
    with pytest.raises(xc.ExecShutdown):
        sched.submit("late", _q_slow, tables)


def test_quarantine_fail_fast():
    from spark_rapids_jni_tpu.faultinj.injector import InjectedDeviceError
    from spark_rapids_jni_tpu.faultinj.resilience import DeviceQuarantined
    tables = {"t": _mktab(100, 0)}

    def q_fatal(tbls):
        raise InjectedDeviceError("ptx trap analog")

    # recovery=False pins the legacy contract this test holds: quarantine
    # is terminal, every later submit fails fast.  The probe-recovery
    # lifecycle (default-on) is covered by tests/test_chaos.py.
    with xc.QueryScheduler(workers=1, recovery=False) as sched:
        tk = sched.submit("fatal", q_fatal, tables, compiled=False)
        with pytest.raises(DeviceQuarantined):
            tk.result(timeout=60)
        # fail-fast on every later submit — the replace-the-executor
        # contract
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            try:
                sched.submit("after", _q_sum, tables, compiled=False)
            except DeviceQuarantined:
                break
            time.sleep(0.01)
        else:
            pytest.fail("quarantine did not fail fast")
    assert metrics.snapshot()["counters"].get("exec.quarantined", 0) >= 1


def test_transient_oom_retries():
    from spark_rapids_jni_tpu.faultinj.injector import InjectedOomError
    tables = {"t": _mktab(100, 0)}
    state = {"n": 0}

    def q_flaky(tbls):
        state["n"] += 1
        if state["n"] == 1:
            raise InjectedOomError("transient")
        return _q_sum(tbls)

    with xc.QueryScheduler(workers=1) as sched:
        out = sched.run("flaky", q_flaky, tables, compiled=False)
    assert int(np.asarray(out.columns[0].data)[0]) == int(
        np.asarray(_q_sum(tables).columns[0].data)[0])
    assert metrics.snapshot()["counters"].get("exec.retries", 0) >= 1


# --- admission ----------------------------------------------------------------


def test_admission_deferred_then_serves():
    tables = {"t": _mktab(5000, 3)}
    est = xc.request_bytes(tables)
    assert est > 0
    oracle = _canon(_q_sum(tables))
    with xc.QueryScheduler(workers=4,
                           inflight_bytes=int(est * 1.5)) as sched:
        tks = [sched.submit(f"q{i}", _q_slow, tables, compiled=False)
               for i in range(4)]
        for tk in tks:
            assert _same(_canon(tk.result(timeout=60)), oracle)
            assert not tk.degraded       # fits the cap → dense path
    snap = metrics.snapshot()["counters"]
    assert snap.get("exec.admission.deferred", 0) >= 1
    assert snap.get("exec.admission.degraded", 0) == 0


def test_admission_deadline_typed():
    ctl = xc.AdmissionController(cap_bytes=1000)
    grant = ctl.admit(800, name="hold")
    with pytest.raises(xc.ExecDeadlineExceeded):
        ctl.admit(500, name="late",
                  deadline=time.monotonic() + 0.05)
    grant.release()
    with ctl.admit(500, name="now") as g:
        assert not g.degrade


# --- plan cache ---------------------------------------------------------------


def test_plan_cache_hit_and_counters():
    tables = {"t": _mktab(1000, 1)}
    cache = xc.PlanCache(cap=4)
    a = _canon(cache.run("s", _q_sum, tables))
    b = _canon(cache.run("s", _q_sum, tables))
    c = _canon(cache.run("s", _q_sum, tables))
    assert _same(a, b) and _same(b, c)
    snap = metrics.snapshot()["counters"]
    assert snap.get("exec.plan_cache.miss") == 1
    assert snap.get("exec.plan_cache.hit") == 2
    # second hit runs the verified raw-dispatch path
    assert snap.get("compiled.replay_run", 0) >= 1


def test_plan_cache_eviction_capacity():
    cache = xc.PlanCache(cap=1)
    t1 = {"t": _mktab(500, 1)}
    t2 = {"t": _mktab(500, 2)}
    a1 = _canon(cache.run("s", _q_sum, t1))
    a2 = _canon(cache.run("s", _q_sum, t2))      # evicts t1's entry
    assert len(cache) == 1
    b1 = _canon(cache.run("s", _q_sum, t1))      # identity miss again
    assert _same(a1, b1) and _same(a1, _canon(_q_sum(t1)))
    assert _same(a2, _canon(_q_sum(t2)))
    snap = metrics.snapshot()["counters"]
    assert snap.get("exec.plan_cache.evictions", 0) >= 2
    # same shape: one capture, the evicted re-entries adopt the warm
    # plan through the size-fingerprint index and revalidate
    assert snap.get("exec.plan_cache.miss") == 1
    assert snap.get("exec.plan_cache.size_hit") == 2
    assert snap.get("exec.plan_cache.revalidate") == 2
    assert not snap.get("exec.plan_cache.hit")


def test_plan_cache_eviction_capacity_no_size_sharing():
    """With size-fingerprint sharing off, refreshed buffers recapture —
    the pre-sharing contract stays available behind the knob."""
    cache = xc.PlanCache(cap=1, share_by_size=False)
    t1 = {"t": _mktab(500, 1)}
    t2 = {"t": _mktab(500, 2)}
    cache.run("s", _q_sum, t1)
    cache.run("s", _q_sum, t2)
    cache.run("s", _q_sum, t1)
    snap = metrics.snapshot()["counters"]
    assert snap.get("exec.plan_cache.miss") == 3
    assert not snap.get("exec.plan_cache.size_hit")


def test_plan_cache_expiry_on_gc():
    cache = xc.PlanCache(cap=4)
    tables = {"t": _mktab(500, 4)}
    cache.run("s", _q_sum, tables)
    assert len(cache) == 1
    del tables
    gc.collect()
    assert len(cache) == 0                  # weakref death evicted it


def test_plan_cache_refreshed_data_size_fp_hit():
    """Refreshed buffers (same shapes, new data) adopt the warm plan via
    the size fingerprint — ONE capture, the adopter revalidated against
    its own tape — and both datasets' results stay correct."""
    cache = xc.PlanCache(cap=4)
    t1 = {"t": _mktab(800, 5)}
    t2 = {"t": _mktab(800, 6)}              # same shape, different data
    a1 = _canon(cache.run("s", _q_sum, t1))
    a2 = _canon(cache.run("s", _q_sum, t2))
    assert _same(a1, _canon(_q_sum(t1)))
    assert _same(a2, _canon(_q_sum(t2)))
    assert not _same(a1, a2)
    snap = metrics.snapshot()["counters"]
    assert snap.get("exec.plan_cache.miss") == 1
    assert snap.get("exec.plan_cache.size_hit") == 1
    assert snap.get("exec.plan_cache.revalidate") == 1
    assert len(cache) == 2                  # distinct identity entries


def test_plan_cache_size_fp_stale_tape_recompiles():
    """A data-DEPENDENT size defeats the shape fingerprint: the adopted
    plan's tape revalidation must catch the mismatch (StaleTapeError)
    and recapture rather than return wrong-shaped results."""
    from spark_rapids_jni_tpu.utils import syncs

    def q_dyn(tbls):
        d = tbls["t"].columns[0].data
        n = syncs.scalar(jnp.sum((d > 50).astype(jnp.int32)))
        return Table([Column(T.DType(T.TypeId.INT32),
                             jnp.arange(n, dtype=jnp.int32))])

    cache = xc.PlanCache(cap=4)
    rng = np.random.default_rng(0)
    t1 = {"t": Table([_mkcol(rng.integers(0, 100, 600))])}
    t2 = {"t": Table([_mkcol(rng.integers(0, 100, 600))])}  # same shape
    a1 = _canon(cache.run("dyn", q_dyn, t1))
    a2 = _canon(cache.run("dyn", q_dyn, t2))
    assert _same(a1, _canon(q_dyn(t1)))
    assert _same(a2, _canon(q_dyn(t2)))
    assert a1[0].shape != a2[0].shape       # sizes really diverged
    snap = metrics.snapshot()["counters"]
    assert snap.get("exec.plan_cache.size_hit") == 1
    assert snap.get("exec.plan_cache.stale", 0) >= 1


def test_plan_cache_single_flight():
    tables = {"t": _mktab(2000, 7)}
    cache = xc.PlanCache(cap=4)
    barrier = threading.Barrier(4)
    outs, errs = [], []

    def worker():
        try:
            barrier.wait(timeout=30)
            outs.append(_canon(cache.run("s", _q_sum, tables)))
        except Exception as e:
            errs.append(e)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errs, errs
    assert all(_same(outs[0], o) for o in outs[1:])
    # one capture total: racing misses coalesced onto one build
    assert metrics.snapshot()["counters"].get("exec.plan_cache.miss") == 1


# --- cross-request coalescing -------------------------------------------------


def _burst(sched, reqs):
    """Submit behind a slow eager blocker so the requests pile up in the
    queue and the dequeuing worker can coalesce them."""
    blocker = sched.submit("blocker", _q_slow, {"t": _mktab(50, 99)},
                           compiled=False)
    tks = [sched.submit(name, qfn, tbls, **kw)
           for name, qfn, tbls, kw in reqs]
    return blocker, tks


def test_coalesced_burst_bit_identical(tpcds_tables, tpcds_oracle):
    """A burst of same-plan TPC-DS requests coalesces into batched
    launches and every response stays bit-identical to serial eager."""
    from spark_rapids_jni_tpu.models import tpcds
    oracle = tpcds_oracle
    reqs = [(q, tpcds.QUERIES[q], tpcds_tables, {})
            for q in QNAMES for _ in range(4)]
    with xc.QueryScheduler(workers=1, coalesce_ms=100) as sched:
        blocker, tks = _burst(sched, reqs)
        blocker.result(timeout=60)
        bad = sum(not _same(_canon(tk.result(timeout=300)), oracle[q])
                  for (q, _, _, _), tk in zip(reqs, tks))
    assert bad == 0
    snap = metrics.snapshot()
    assert snap["counters"].get("exec.completed", 0) == 13
    hist = snap["histograms"].get("exec.batch.size")
    assert hist is not None and hist["max"] >= 2
    assert "exec.batch.coalesce_wait_ms" in snap["histograms"]
    # the counter invariant survives coalescing: every compiled request
    # is accounted as exactly one of hit/miss/size_hit
    c = snap["counters"]
    assert (c.get("exec.plan_cache.hit", 0)
            + c.get("exec.plan_cache.miss", 0)
            + c.get("exec.plan_cache.size_hit", 0)) == 12


def test_mixed_shapes_do_not_coalesce():
    """Same query over different-shape tables ⇒ different coalesce keys
    ⇒ no batch ever forms (batching must never mix programs)."""
    t_a = {"t": _mktab(500, 1)}
    t_b = {"t": _mktab(700, 2)}              # different shape
    with xc.QueryScheduler(workers=1, coalesce_ms=100) as sched:
        blocker, tks = _burst(sched, [("s", _q_sum, t_a, {}),
                                      ("s", _q_sum, t_b, {}),
                                      ("s", _q_sum, t_a, {}),
                                      ("s", _q_sum, t_b, {})])
        blocker.result(timeout=60)
        outs = [_canon(tk.result(timeout=60)) for tk in tks]
    assert _same(outs[0], _canon(_q_sum(t_a))) and _same(outs[0], outs[2])
    assert _same(outs[1], _canon(_q_sum(t_b))) and _same(outs[1], outs[3])
    snap = metrics.snapshot()["histograms"]
    hist = snap.get("exec.batch.size")
    # same-shape duplicates may batch; across shapes never
    assert hist is None or hist["max"] <= 2


def test_deadline_fires_during_coalesce_window():
    """A request whose deadline passes while it sits in a coalesce batch
    gets the typed queue-deadline error; its batch-mates still serve."""
    tables = {"t": _mktab(400, 3)}
    oracle = _canon(_q_sum(tables))
    with xc.QueryScheduler(workers=1, coalesce_ms=200) as sched:
        blocker, (tk_ok, tk_dl) = _burst(
            sched, [("s", _q_sum, tables, {}),
                    ("s", _q_sum, tables, {"timeout_s": 0.01})])
        blocker.result(timeout=60)
        assert _same(_canon(tk_ok.result(timeout=60)), oracle)
        with pytest.raises(xc.ExecDeadlineExceeded) as ei:
            tk_dl.result(timeout=60)
        assert ei.value.stage == "queue"
    assert metrics.snapshot()["counters"].get("exec.deadline.queue", 0) >= 1


def test_batch_admission_split_over_cap():
    """A coalesced batch whose distinct working sets exceed the in-flight
    cap splits into cap-sized sub-batches instead of blowing the gate."""
    tabs = [{"t": _mktab(2000, 10 + i)} for i in range(4)]   # same shape
    one = xc.request_bytes(tabs[0])
    with xc.QueryScheduler(workers=1, coalesce_ms=100,
                           inflight_bytes=int(one * 2.5)) as sched:
        blocker, tks = _burst(
            sched, [("s", _q_sum, t, {}) for t in tabs])
        blocker.result(timeout=60)
        for t, tk in zip(tabs, tks):
            assert _same(_canon(tk.result(timeout=60)), _canon(_q_sum(t)))
    snap = metrics.snapshot()["counters"]
    assert snap.get("exec.batch.split", 0) >= 1
    assert snap.get("exec.admission.degraded", 0) == 0


def test_batched_vmap_distinct_buffers():
    """Distinct same-shape working sets with WARM verified plans stack
    onto the vmapped program: one launch, per-request results identical
    to per-request dispatch."""
    tabs = [{"t": _mktab(1500, 20 + i)} for i in range(3)]   # same shape
    plans = xc.PlanCache(cap=8)
    oracles = []
    for t in tabs:
        plans.run("s", _q_sum, t)
        oracles.append(_canon(plans.run("s", _q_sum, t)))  # 2nd → verified
    with xc.QueryScheduler(workers=1, coalesce_ms=100,
                           plan_cache=plans) as sched:
        blocker, tks = _burst(
            sched, [("s", _q_sum, t, {}) for t in tabs])
        blocker.result(timeout=60)
        for o, tk in zip(oracles, tks):
            assert _same(_canon(tk.result(timeout=60)), o)
    snap = metrics.snapshot()["counters"]
    assert snap.get("compiled.batch_replay", 0) >= 1
    assert snap.get("compiled.batch_parity_check", 0) >= 1
    assert snap.get("compiled.batch_parity_reject", 0) == 0


# --- prefetch -----------------------------------------------------------------


def test_prefetch_hit_and_inline_miss():
    pf = xc.Prefetcher(depth=2)
    try:
        assert pf.stage("a", lambda: {"t": _mktab(200, 8)})
        assert pf._slots["a"]["done"].wait(30)   # staged, not racing take
        got = pf.take("a")
        assert _same(_canon(_q_sum(got)), _canon(_q_sum({"t": _mktab(200, 8)})))
        got = pf.take("nope", loader=lambda: {"t": _mktab(100, 9)})
        assert got["t"].columns[0].data.shape[0] == 100
    finally:
        pf.close()
    snap = metrics.snapshot()["counters"]
    assert snap.get("exec.prefetch.hit") == 1
    assert snap.get("exec.prefetch.miss") == 1


def test_prefetch_take_before_stage_race():
    """Regression: take() claiming a still-queued slot must load inline
    instead of waiting for a staging pass that will never run."""
    pf = xc.Prefetcher(depth=2)
    try:
        for i in range(50):
            pf.stage(i, lambda i=i: i * 2)
            t0 = time.monotonic()
            assert pf.take(i, loader=lambda i=i: i * 2) == i * 2
            assert time.monotonic() - t0 < 5
    finally:
        pf.close()


def test_prefetch_depth_bound():
    pf = xc.Prefetcher(depth=1)
    try:
        ev = threading.Event()
        assert pf.stage("slow", lambda: (ev.wait(10), 1)[1])
        assert not pf.stage("b", lambda: 2)      # buffer full → rejected
        ev.set()
        assert pf.take("slow") == 1
    finally:
        pf.close()
    assert metrics.snapshot()["counters"].get("exec.prefetch.rejected") == 1


# --- thread-safety regressions ------------------------------------------------


def test_spillable_arrays_concurrent_faultback():
    """Two threads racing get() on a spilled payload must both see the
    device arrays (the _host=None race fixed with this subsystem)."""
    from spark_rapids_jni_tpu.memory.spill import SpillableArrays
    data = np.arange(4096, dtype=np.int32)
    for _ in range(20):
        sa = SpillableArrays("t", {"d": jnp.asarray(data)})
        assert sa.spill() > 0
        outs, errs = [], []

        def reader():
            try:
                outs.append(np.asarray(sa.get()["d"]))
            except Exception as e:
                errs.append(e)
        ts = [threading.Thread(target=reader) for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        assert not errs, errs
        assert all(np.array_equal(o, data) for o in outs)


def test_weakidmemo_concurrent_capped_puts():
    from spark_rapids_jni_tpu.utils.hostcache import WeakIdMemo
    evictions = []
    memo = WeakIdMemo(cap_bytes=64 * 100,
                      on_evict=lambda: evictions.append(1))
    keys = [np.zeros(1, np.int8) for _ in range(200)]   # weakref-able keys
    errs = []

    def writer(lo):
        try:
            for i in range(lo, lo + 50):
                memo.put((keys[i],), np.zeros(64, np.uint8))
                memo.get((keys[i],))
        except Exception as e:
            errs.append(e)

    ts = [threading.Thread(target=writer, args=(i * 50,)) for i in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert not errs, errs
    assert memo.nbytes() <= 64 * 100 + 64    # cap respected (±1 in flight)
    assert evictions                         # capped storm did evict


def test_syncs_capture_is_thread_local():
    """Two threads capturing concurrently must record onto their own
    tapes (a process-global mode would interleave them)."""
    from spark_rapids_jni_tpu.utils import syncs
    results = {}
    errs = []

    def run(tid, vals):
        try:
            tape = []
            with syncs.capture(tape):
                for v in vals:
                    syncs.scalar(jnp.asarray(v, jnp.int32))
            results[tid] = tape
        except Exception as e:
            errs.append(e)

    t1 = threading.Thread(target=run, args=(1, [11, 12, 13] * 20))
    t2 = threading.Thread(target=run, args=(2, [27, 28] * 30))
    t1.start(); t2.start()
    t1.join(timeout=30); t2.join(timeout=30)
    assert not errs, errs
    assert results[1] == [11, 12, 13] * 20
    assert results[2] == [27, 28] * 30


def test_exec_enabled_gate(monkeypatch):
    monkeypatch.delenv("SRJT_EXEC", raising=False)
    assert not xc.enabled()
    monkeypatch.setenv("SRJT_EXEC", "1")
    assert xc.enabled()
    monkeypatch.setenv("SRJT_EXEC", "off")
    assert not xc.enabled()


# --- lifecycle tracing + incidents + SLO -------------------------------------


def test_request_lifecycle_traced_end_to_end():
    from spark_rapids_jni_tpu.utils import flight
    flight.reset()
    tables = {"t": _mktab(100, 0)}
    with xc.QueryScheduler(workers=1) as sched:
        tk = sched.submit("lc", _q_sum, tables)
        tk.result(timeout=60)
    assert tk.rid == "lc#0"
    kinds = [e["kind"] for e in flight.events(request_id=tk.rid)]
    assert kinds[0] == "exec.submit"
    assert "exec.dequeue" in kinds
    assert kinds[-1] == "exec.resolve"
    resolve = flight.events(request_id=tk.rid)[-1]
    assert resolve["outcome"] == "ok" and resolve["e2e_ms"] >= 0
    # per-stage attribution: the ticket carries every stage in seconds,
    # and the histograms carry the same family in ms
    for st in ("queue", "admission", "dispatch", "ready"):
        assert f"{st}_s" in tk.timings
    hists = metrics.snapshot()["histograms"]
    for st in ("queue", "admission", "dispatch", "ready"):
        assert hists[f"exec.stage.{st}_ms"]["count"] >= 1


def test_coalesced_batch_links_member_rids(tpcds_tables):
    from spark_rapids_jni_tpu.models import tpcds
    from spark_rapids_jni_tpu.utils import flight
    flight.reset()
    plans = xc.PlanCache()
    for _ in range(2):                      # warm + verify the plan
        jax.block_until_ready(plans.run("q3", tpcds.QUERIES["q3"],
                                        tpcds_tables))
    with xc.QueryScheduler(workers=1, plan_cache=plans,
                           coalesce_ms=200) as sched:
        blocker = sched.submit("s", _q_slow, {"t": _mktab(100, 0)},
                               compiled=False)
        tks = [sched.submit("q3", tpcds.QUERIES["q3"], tpcds_tables)
               for _ in range(3)]
        blocker.result(timeout=60)
        for tk in tks:
            tk.result(timeout=120)
    rids = [tk.rid for tk in tks]
    launches = [e for e in flight.events()
                if e["kind"] == "exec.batch.launch"]
    assert launches and set(launches[0]["batch"]) == set(rids)
    for tk in tks:
        assert tk.batch_rids is not None and set(tk.batch_rids) == set(rids)


def test_deadline_breach_dumps_incident_snapshot(tmp_path, monkeypatch):
    import json
    from spark_rapids_jni_tpu.utils import flight
    monkeypatch.setenv("SRJT_INCIDENT_DIR", str(tmp_path))
    flight.reset()
    tables = {"t": _mktab(100, 0)}
    with xc.QueryScheduler(workers=1, queue_depth=4) as sched:
        blocker = sched.submit("s", _q_slow, tables, compiled=False)
        tk = sched.submit("dl", _q_slow, tables, compiled=False,
                          timeout_s=0.001)
        with pytest.raises(xc.ExecDeadlineExceeded):
            tk.result(timeout=60)
        blocker.result(timeout=60)
    snaps = sorted(tmp_path.glob("incident-deadline-*.json"))
    assert snaps, "deadline breach must dump a snapshot"
    with open(snaps[0]) as f:
        snap = json.load(f)
    assert snap["kind"] == "deadline"
    assert snap["request_id"] == tk.rid
    mine = [e for e in snap["events"] if e.get("rid") == tk.rid]
    assert {"exec.submit", "exec.resolve"} <= {e["kind"] for e in mine}
    # live serving state rode along via the registered probes
    assert "scheduler.queue_depth" in snap["probes"]


def test_default_deadline_env(monkeypatch):
    monkeypatch.setenv("SRJT_EXEC_DEADLINE", "0.001")
    tables = {"t": _mktab(100, 0)}
    with xc.QueryScheduler(workers=1, queue_depth=4) as sched:
        assert sched.default_timeout_s == 0.001
        blocker = sched.submit("s", _q_slow, tables, compiled=False,
                               timeout_s=600)
        tk = sched.submit("dl", _q_slow, tables, compiled=False)
        with pytest.raises(xc.ExecDeadlineExceeded):
            tk.result(timeout=60)          # env deadline applied
        blocker.result(timeout=60)


def test_slo_watchdog_breach_and_cooldown():
    slo = xc.SloWatchdog(thresholds={"p95_ms": 10.0}, window_s=60,
                         min_n=4, cooldown_s=3600)
    for _ in range(3):
        assert slo.observe("q", 100.0) == []     # below min population
    fired = slo.observe("q", 100.0, request_id="q#3")
    assert len(fired) == 1 and fired[0]["objective"] == "p95_ms"
    assert slo.observe("q", 100.0) == []         # cooldown holds
    st = slo.class_status("q")
    assert st["breached"] and st["objectives"]["p95_ms"]["breached"]


def test_slo_watchdog_rates_and_disabled():
    assert not xc.SloWatchdog(thresholds={}).enabled()
    slo = xc.SloWatchdog(thresholds={"error_rate": 0.25}, min_n=4,
                         cooldown_s=3600)
    for outcome in ("ok", "ok", "error", "error"):
        fired = slo.observe("q", 1.0, outcome=outcome)
    assert fired and fired[0]["objective"] == "error_rate"
    assert slo.class_status("q")["error_rate"] == 0.5


def test_scheduler_fires_slo_breach_incident(tmp_path, monkeypatch):
    monkeypatch.setenv("SRJT_INCIDENT_DIR", str(tmp_path))
    monkeypatch.setenv("SRJT_SLO_P95_MS", "0.000001")
    monkeypatch.setenv("SRJT_SLO_MIN_N", "2")
    tables = {"t": _mktab(100, 0)}
    with xc.QueryScheduler(workers=1) as sched:
        for _ in range(3):
            sched.submit("slowq", _q_sum, tables).result(timeout=60)
    assert metrics.snapshot()["counters"].get("exec.slo.breach", 0) >= 1
    assert list(tmp_path.glob("incident-slo_breach-*.json"))


def test_ops_state_and_ops_report():
    import importlib.util
    import os as _os
    path = _os.path.join(_os.path.dirname(__file__), "..", "tools",
                         "ops_report.py")
    spec = importlib.util.spec_from_file_location("ops_report", path)
    ops_report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ops_report)
    tables = {"t": _mktab(100, 0)}
    with xc.QueryScheduler(workers=2) as sched:
        sched.submit("r", _q_sum, tables).result(timeout=60)
        st = sched.ops_state()
        assert st["workers"] == 2 and st["queue_depth"] == 0
        assert "plan_cache" in st and "slo" in st
        text = ops_report.report(sched)
    assert "serving state" in text
    assert "latency attribution" in text
    assert "queue" in text


# --- plan-tree lowered queries through the serving runtime -------------------


def test_plan_lowered_queries_serve_bit_identical(tpcds_tables,
                                                  tpcds_oracle):
    """A qfn lowered from an optimized plan tree rides the scheduler
    unchanged — named by its structural plan fingerprint, cached by the
    plan cache, and bit-identical to the hand-fused oracle."""
    from spark_rapids_jni_tpu.models import tpcds_plans
    qfns = {q: tpcds_plans.plan_fn(q)[0] for q in QNAMES}
    with xc.QueryScheduler(workers=2) as sched:
        for _ in range(2):               # second round: plan-cache hits
            for q in QNAMES:
                tk = sched.submit(qfns[q].plan_fingerprint, qfns[q],
                                  tpcds_tables)
                assert _same(_canon(tk.result(timeout=300)),
                             tpcds_oracle[q])
    snap = metrics.snapshot()["counters"]
    assert snap.get("exec.completed", 0) == 6
    # 3 distinct plan fingerprints: one compile each, then pure hits
    assert snap.get("exec.plan_cache.miss", 0) == 3
    assert snap.get("exec.plan_cache.hit", 0) == 3
