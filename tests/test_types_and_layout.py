"""Tests for the dtype system and the JCUDF row-layout calculator.

The layout expectations are the worked examples from the reference's format
spec (RowConversion.java:60-90) — computed by hand here, not copied.
"""

import numpy as np
import pytest

import spark_rapids_jni_tpu as sr
from spark_rapids_jni_tpu.rowconv import layout as L


def test_dtype_itemsize_and_alignment():
    assert sr.int8.itemsize == 1 and sr.int8.row_alignment == 1
    assert sr.int64.itemsize == 8 and sr.int64.row_alignment == 8
    assert sr.bool8.itemsize == 1
    assert sr.timestamp_days.storage == np.dtype(np.int32)
    assert sr.timestamp_ms.storage == np.dtype(np.int64)
    # string slot: 8 bytes, 4-byte aligned (row_conversion.cu:1342-1350)
    assert sr.string.itemsize == 8 and sr.string.row_alignment == 4
    assert sr.decimal32(-2).storage == np.dtype(np.int32)
    assert sr.decimal64(-4).storage == np.dtype(np.int64)


def test_dtype_scale_only_for_decimals():
    with pytest.raises(ValueError):
        sr.DType(sr.TypeId.INT32, scale=-2)


def test_layout_javadoc_example_bool_int16_int32():
    # | A_0 | P | B_0 B_1 | C_0..C_3 | V0 | P*7 |  → 16 bytes
    lay = L.compute_row_layout([sr.bool8, sr.int16, sr.int32])
    assert lay.column_starts == (0, 2, 4)
    assert lay.validity_offset == 8
    assert lay.validity_bytes == 1
    assert lay.fixed_row_size == 16


def test_layout_javadoc_example_reordered():
    # C, B, A → | C*4 | B*2 | A | V0 | = 8 bytes, no padding
    lay = L.compute_row_layout([sr.int32, sr.int16, sr.bool8])
    assert lay.column_starts == (0, 4, 6)
    assert lay.validity_offset == 7
    assert lay.fixed_row_size == 8


def test_layout_string_slot_alignment():
    # int8 at 0, string slot aligned to 4 → starts at 4, occupies 8
    lay = L.compute_row_layout([sr.int8, sr.string, sr.int64])
    assert lay.column_starts == (0, 4, 16)
    assert lay.variable_column_indices == (1,)
    assert not lay.fixed_width_only


def test_layout_validity_byte_per_8_columns():
    lay = L.compute_row_layout([sr.int8] * 9)
    assert lay.validity_bytes == 2
    assert lay.validity_offset == 9
    assert lay.fixed_row_size == 16


def test_row_size_limit_enforced():
    # 1KB hard limit, RowConversion.java:98-99
    with pytest.raises(ValueError, match="1024"):
        L.compute_row_layout([sr.int64] * 200)


def test_build_batches_single():
    b = L.build_batches(np.full(100, 16, dtype=np.int64))
    assert b.num_batches == 1
    assert b.row_boundaries == (0, 100)
    assert b.batch_bytes == (1600,)
    np.testing.assert_array_equal(
        b.row_offsets_within_batch[0], np.arange(101) * 16)


def test_build_batches_splits_on_limit_and_32_row_multiple():
    # 100 rows × 16B with a 1000-byte cap → 62-row capacity, rounded down to 32
    b = L.build_batches(np.full(100, 16, dtype=np.int64), max_batch_bytes=1000)
    assert b.row_boundaries[1] % 32 == 0
    assert all(x <= 1000 for x in b.batch_bytes)
    assert b.row_boundaries[-1] == 100
    assert sum(b.batch_bytes) == 1600


def test_build_batches_row_too_big():
    with pytest.raises(ValueError):
        L.build_batches(np.asarray([10, 2000, 10]), max_batch_bytes=1000)
