"""Streaming ingest + incremental maintenance (stream/) tests.

The subsystem's contract is that incremental refresh changes COST, never
results: a registered view refreshed over appended row groups must be
bit-identical to a from-scratch recompute of the same plan — including
through the concurrent scheduler — and anything the delta algebra cannot
maintain must fall back to full recompute (visibly, via counters), never
silently drift.  Held here:

* delta-scan boundaries — empty delta, delta spanning a file boundary,
  watermark persistence, the ``until`` snapshot bound, extend-file
  prefix validation, pruning composition.
* merge-state equivalence vs full recompute for every supported agg,
  null-heavy partitions included; unmerged states finalize bit-identical
  for ALL aggs (incl. var/std and f64 sums).
* view classification — maintainable shapes refresh incrementally and
  bit-exactly; window shapes, grand totals, and non-exact aggregates
  fall back (``stream.view.fallback``); ``allow_approx`` opts var views
  back in at allclose fidelity.
* build-index append-extend — field-identical to rebuild when appended
  keys stay in the window; None (rebuild signal) otherwise.
* refresh-while-serving differential through ``exec/``.
"""

import io
import threading

import numpy as np
import pytest

import jax.numpy as jnp

pa = pytest.importorskip("pyarrow")
pq = pytest.importorskip("pyarrow.parquet")

from benchmarks import tpcds_data
from spark_rapids_jni_tpu import exec as xc
from spark_rapids_jni_tpu import types as T
from spark_rapids_jni_tpu.column import Column, Table, force_column
from spark_rapids_jni_tpu.models import tpcds, tpcds_plans
from spark_rapids_jni_tpu.ops import apply_boolean_mask
from spark_rapids_jni_tpu.ops import groupby as G
from spark_rapids_jni_tpu.ops import join_plan as JP
from spark_rapids_jni_tpu.ops.copying import concat_tables
from spark_rapids_jni_tpu.plan import ir, lower
from spark_rapids_jni_tpu.plan import stats as plan_stats
from spark_rapids_jni_tpu.stream import DeltaTable, ViewRegistry
from spark_rapids_jni_tpu.utils import metrics


@pytest.fixture(autouse=True)
def _metrics_on():
    metrics.set_enabled(True)
    metrics.reset()
    yield
    metrics.reset()
    metrics.set_enabled(None)


def _blob(n, start=0, row_group_size=4):
    tab = pa.table({
        "k": pa.array(np.arange(start, start + n, dtype=np.int32)),
        "v": pa.array((np.arange(start, start + n) * 3).astype(np.int64)),
    })
    buf = io.BytesIO()
    pq.write_table(tab, buf, compression="SNAPPY", use_dictionary=False,
                   row_group_size=row_group_size)
    return buf.getvalue()


def _bitcmp(a: Table, b: Table, tag=""):
    assert a.num_rows == b.num_rows, (tag, a.num_rows, b.num_rows)
    assert len(a.columns) == len(b.columns)
    for i in range(len(a.columns)):
        x, y = force_column(a[i]), force_column(b[i])
        assert x.dtype.id == y.dtype.id, (tag, i)
        np.testing.assert_array_equal(np.asarray(x.data),
                                      np.asarray(y.data),
                                      err_msg=f"{tag} col {i} data")
        if x.offsets is not None:
            np.testing.assert_array_equal(np.asarray(x.offsets),
                                          np.asarray(y.offsets),
                                          err_msg=f"{tag} col {i} offsets")
        xv = None if x.validity is None else np.asarray(x.validity)
        yv = None if y.validity is None else np.asarray(y.validity)
        nn = np.ones(x.data.shape[0], bool)
        np.testing.assert_array_equal(nn if xv is None else xv,
                                      nn if yv is None else yv,
                                      err_msg=f"{tag} col {i} validity")


# --- delta scans -------------------------------------------------------------


class TestDeltaScan:
    def test_empty_delta_keeps_schema(self):
        d = DeltaTable("t", files=[_blob(10)])
        wm = d.watermark()
        t = d.scan(since=wm)
        assert t.num_rows == 0 and t.num_columns == 2
        assert metrics.counter_value("stream.delta.rowgroups") == 0
        assert d.schema() == ["k", "v"]

    def test_delta_spans_file_boundary(self):
        d = DeltaTable("t", files=[_blob(10)])          # groups [4, 4, 2]
        wm = (1,)                                       # consumed 4 rows
        d.append_file(_blob(6, start=100))              # groups [4, 2]
        t = d.scan(since=wm)
        # rest of file 0 (6 rows) + all of file 1 (6 rows)
        assert t.num_rows == 12
        np.testing.assert_array_equal(
            np.asarray(force_column(t[0]).data)[:6], np.arange(4, 10))
        assert metrics.counter_value("stream.delta.rowgroups") == 4

    def test_watermark_persistence_and_epoch(self):
        d = DeltaTable("t", files=[_blob(10)])
        assert d.epoch == 1
        wm = d.watermark()
        assert wm == (3,)
        a = d.scan(since=wm)
        b = d.scan(since=wm)
        assert a.num_rows == b.num_rows == 0      # watermark is stable
        d.append_file(_blob(4, start=50))
        assert d.epoch == 2 and d.watermark() == (3, 1)
        assert d.scan(since=wm).num_rows == 4
        assert d.total_rows(wm) == 4 and d.total_rows() == 14
        assert d.delta_bytes(wm) > 0
        assert d.delta_bytes(d.watermark()) == 0

    def test_until_bounds_the_snapshot(self):
        d = DeltaTable("t", files=[_blob(10)])
        assert d.scan(until=(1,)).num_rows == 4
        d.append_file(_blob(6, start=100))
        # a snapshot taken before the append sees none of it
        assert d.scan(since=(1,), until=(3,)).num_rows == 6

    def test_extend_file_prefix_validation(self):
        d = DeltaTable("t", files=[_blob(8)])           # groups [4, 4]
        d.extend_file(0, _blob(12))                     # groups [4, 4, 4]
        assert d.watermark() == (3,)
        with pytest.raises(ValueError):
            d.extend_file(0, _blob(12, row_group_size=5))
        base = tpcds_data.append_rows(8, seed=3, row_group_size=4)
        d2 = DeltaTable("f", files=[base])
        wm = d2.watermark()
        ext = tpcds_data.append_rows(4, seed=4, row_group_size=4, base=base)
        d2.extend_file(0, ext)
        assert d2.scan(since=wm).num_rows == 4

    def test_pruning_composes_with_delta_scan(self):
        d = DeltaTable("t", files=[_blob(16)])          # k sorted per group
        wm = d.watermark()
        d.append_file(_blob(16, start=100))
        t = d.scan(columns=["v"], since=wm,
                   rowgroup_predicate=[("k", "ge", 108)])
        assert t.num_columns == 1
        assert t.num_rows == 8
        assert metrics.counter_value("plan.scan.rowgroups_pruned") == 2


# --- mergeable aggregate states ---------------------------------------------


def _state_tab(n, seed, null_frac=0.3):
    r = np.random.default_rng(seed)
    valid = r.random(n) > null_frac
    return Table([
        Column(T.int32, jnp.asarray(r.integers(0, 7, n).astype(np.int32))),
        Column(T.int64, jnp.asarray(r.integers(-50, 50, n).astype(np.int64)),
               validity=jnp.asarray(valid)),
        Column.from_values(T.float64, jnp.asarray(r.normal(0, 10, n)),
                           validity=jnp.asarray(valid)),
    ])


_ALL_AGGS = [(1, "sum"), (1, "count"), (1, "min"), (1, "max"), (1, "mean"),
             (1, "var"), (1, "std"), (2, "sum"), (2, "mean"), (2, "min"),
             (2, "max"), (2, "var"), (2, "std")]


class TestMergeStates:
    def _spec(self, tab):
        return G.plan_aggregate_states(
            _ALL_AGGS, {i: c.dtype for i, c in enumerate(tab.columns)}, 1)

    def test_merge_equivalence_all_aggs(self):
        # partition B is null-heavy (90%) so all-null groups and
        # validity-merging actually exercise
        a, b = _state_tab(400, 1), _state_tab(250, 2, null_frac=0.9)
        spec = self._spec(a)
        merged = G.finalize_aggregate_states(
            spec, G.merge_aggregate_states(
                spec,
                G.partial_aggregate_states(a, [0], _ALL_AGGS, spec=spec),
                G.partial_aggregate_states(b, [0], _ALL_AGGS, spec=spec)))
        expect = G.groupby_aggregate(concat_tables([a, b]), [0], _ALL_AGGS)
        assert merged.num_rows == expect.num_rows
        for i, o in enumerate(spec.outs):
            x = force_column(expect[1 + i])
            y = force_column(merged[1 + i])
            if o.exact:
                np.testing.assert_array_equal(
                    np.asarray(x.data), np.asarray(y.data),
                    err_msg=f"{o.agg} exact")
            else:
                np.testing.assert_allclose(
                    np.asarray(x.values()), np.asarray(y.values()),
                    rtol=1e-9, atol=1e-9, err_msg=o.agg)
            xv = None if x.validity is None else np.asarray(x.validity)
            yv = None if y.validity is None else np.asarray(y.validity)
            nn = np.ones(expect.num_rows, bool)
            np.testing.assert_array_equal(nn if xv is None else xv,
                                          nn if yv is None else yv,
                                          err_msg=f"{o.agg} validity")

    def test_unmerged_finalize_bit_identical(self):
        # an UNMERGED state must reproduce groupby_aggregate exactly for
        # EVERY agg — float sums, var, std included
        tab = _state_tab(500, 5)
        spec = self._spec(tab)
        got = G.finalize_aggregate_states(
            spec, G.partial_aggregate_states(tab, [0], _ALL_AGGS, spec=spec))
        _bitcmp(got, G.groupby_aggregate(tab, [0], _ALL_AGGS), "unmerged")

    def test_empty_partition_merge_is_identity(self):
        a = _state_tab(300, 7)
        spec = self._spec(a)
        sa = G.partial_aggregate_states(a, [0], _ALL_AGGS, spec=spec)
        se = G.partial_aggregate_states(_state_tab(0, 8), [0], _ALL_AGGS,
                                        spec=spec)
        assert se.num_rows == 0
        _bitcmp(G.finalize_aggregate_states(
                    spec, G.merge_aggregate_states(spec, sa, se)),
                G.finalize_aggregate_states(spec, sa), "empty-merge")
        assert G.merge_aggregate_states(spec, None, sa) is sa

    def test_string_keys_and_exactness_plan(self):
        r = np.random.default_rng(9)
        keys = Column.strings_from_list([f"g{i % 5}" for i in range(200)])
        vals = Column(T.int64,
                      jnp.asarray(r.integers(0, 99, 200).astype(np.int64)))
        tab = Table([keys, vals])
        aggs = [(1, "sum"), (1, "mean"), (1, "count")]
        spec = G.plan_aggregate_states(
            aggs, {i: c.dtype for i, c in enumerate(tab.columns)}, 1)
        assert spec.exact     # int sum/mean/count are all merge-exact
        # merge across two halves built by row masks
        lo = apply_boolean_mask(tab, jnp.arange(200) < 120)
        hi = apply_boolean_mask(tab, jnp.arange(200) >= 120)
        got = G.finalize_aggregate_states(
            spec, G.merge_aggregate_states(
                spec, G.partial_aggregate_states(lo, [0], aggs, spec=spec),
                G.partial_aggregate_states(hi, [0], aggs, spec=spec)))
        _bitcmp(got, G.groupby_aggregate(tab, [0], aggs), "strkeys")
        assert not G.merge_exact("sum", T.float64)
        assert not G.merge_exact("var", T.int64)
        assert G.merge_exact("min", T.float64)

    def test_rejects_unsupported(self):
        tab = _state_tab(10, 1)
        with pytest.raises(ValueError):
            G.plan_aggregate_states([(1, "first")],
                                    {1: tab[1].dtype}, 1)
        with pytest.raises(ValueError):
            G.partial_aggregate_states(tab, [], [(1, "sum")])


# --- build-index append-extend ----------------------------------------------


class TestBuildIndexExtend:
    @pytest.mark.parametrize("with_valid", [False, True])
    def test_extend_identity_vs_rebuild(self, with_valid):
        r = np.random.default_rng(3)
        base = np.r_[100, 159, r.integers(100, 160, 300)].astype(np.int32)
        base = jnp.asarray(base)        # pins the dense window to [100,159]
        delta = jnp.asarray(r.integers(100, 160, 80).astype(np.int32))
        bv = jnp.asarray(np.r_[True, True, r.random(300) > 0.15]) \
            if with_valid else None
        dv = jnp.asarray(r.random(80) > 0.15) if with_valid else None
        ix = JP._build_index(base, bv, True, False)
        assert ix.kind == "dense"
        ext = JP.extend_build_index(ix, delta, dv, 302)
        ref = JP._build_index(
            jnp.concatenate([base, delta]),
            None if bv is None else jnp.concatenate([bv, dv]), True, False)
        assert ext is not None
        assert (ext.kind, ext.n_valid, ext.kmin, ext.span, ext.unique) == \
               (ref.kind, ref.n_valid, ref.kmin, ref.span, ref.unique)
        for mine, theirs in ((ext.row_ids, ref.row_ids),
                             (ext.lut_lo, ref.lut_lo),
                             (ext.lut_cnt, ref.lut_cnt)):
            np.testing.assert_array_equal(np.asarray(mine),
                                          np.asarray(theirs))

    def test_extend_edges(self):
        base = jnp.asarray(np.arange(100, 130, dtype=np.int32))
        ix = JP._build_index(base, None, True, False)
        # out-of-window key → rebuild signal
        assert JP.extend_build_index(
            ix, jnp.asarray(np.array([500], np.int32)), None, 30) is None
        # out-of-window but NULL key → extend still applies
        got = JP.extend_build_index(
            ix, jnp.asarray(np.array([500, 110], np.int32)),
            jnp.asarray(np.array([False, True])), 30)
        assert got is not None and got.n_valid == 31
        # empty delta → same index
        assert JP.extend_build_index(ix, jnp.zeros(0, jnp.int32),
                                     None, 30) is ix
        # sorted engine → rebuild signal
        six = JP._build_index(base, None, False, False)
        assert JP.extend_build_index(six, base, None, 30) is None


# --- view registry -----------------------------------------------------------


def _mini_files():
    return tpcds_data.generate(n_sales=12_000, n_items=400, seed=11,
                               row_group_size=1024)


def _cents_view_plan():
    j = ir.Join(ir.Join(ir.Scan("store_sales"), ir.Scan("item"),
                        ("ss_item_sk",), ("i_item_sk",)),
                ir.Scan("date_dim"), ("ss_sold_date_sk",), ("d_date_sk",))
    f = ir.Filter(j, ir.And((
        ir.Cmp("==", ir.Col("i_manufact_id"), ir.Lit(436)),
        ir.Cmp("==", ir.Col("d_moy"), ir.Lit(11)))))
    keys = ("d_year", "i_brand_id", "i_brand")
    return ir.Sort(ir.Aggregate(f, keys, (
        ("ss_sales_price_cents", "sum", "sum_cents"),
        ("ss_quantity", "mean", "avg_qty"),
        ("ss_quantity", "count", "n"))), keys)


def _registry(files, **kw):
    tables = tpcds.load_tables(files)
    delta = DeltaTable("store_sales", files=[files["store_sales"]])
    statics = {k: tables[k] for k in ("item", "date_dim", "store")}
    schemas = {k: tpcds_plans.TABLE_SCHEMAS[k] for k in statics}
    return delta, ViewRegistry(delta, statics, schemas, **kw), statics, \
        schemas


def _oracle(reg, v):
    cat = lower.TableCatalog(
        {**reg.statics, reg.delta.name: reg.delta.scan()},
        reg.schemas)
    return lower.execute(v.tree, cat, record_stats=False)


class TestViewRegistry:
    def test_incremental_refresh_bit_identical(self):
        files = _mini_files()
        delta, reg, _, _ = _registry(files)
        v = reg.register_view(_cents_view_plan(), name="q3c")
        assert v.kind == "incremental" and v.exact, v.reason
        _bitcmp(reg.refresh(v), _oracle(reg, v), "epoch0")
        for e in (1, 2):
            delta.append_file(tpcds_data.append_rows(
                12_000 // 64, seed=100 + e, n_items=400,
                row_group_size=1024))
            c0 = metrics.counter_value("stream.delta.rowgroups")
            got = reg.refresh(v)
            assert metrics.counter_value("stream.delta.rowgroups") - c0 == 1
            _bitcmp(got, _oracle(reg, v), f"epoch{e}")
        assert metrics.counter_value("stream.refresh.incremental") == 2
        # re-registering the same plan returns the same view
        assert reg.register_view(_cents_view_plan()) is v
        assert reg.stats()["incremental"] == 1
        reg.close()

    def test_fallbacks_window_grand_total_approx(self):
        files = _mini_files()
        _, reg, _, _ = _registry(files)
        w = reg.register_view(ir.Aggregate(
            ir.Window(ir.Scan("store_sales"), "row_number",
                      ("ss_store_sk",), ("ss_sold_date_sk",), "rn"),
            ("ss_store_sk",), (("rn", "max", "max_rn"),)), name="win")
        assert w.kind == "full" and "Window" in w.reason
        g = reg.register_view(ir.Aggregate(
            ir.Scan("store_sales"), (),
            (("ss_quantity", "sum", "s"),)), name="total")
        assert g.kind == "full" and g.reason == "grand_total"
        a = reg.register_view(ir.Aggregate(
            ir.Scan("store_sales"), ("ss_store_sk",),
            (("ss_ext_sales_price", "var", "v"),)), name="varv")
        assert a.kind == "full" and a.reason.startswith("approx")
        assert metrics.counter_value("stream.view.fallback") == 3
        # full views still serve correct results
        _bitcmp(reg.refresh(w), _oracle(reg, w), "window")
        assert metrics.counter_value("stream.refresh.full") >= 1
        reg.close()

    def test_allow_approx_var_view(self):
        files = _mini_files()
        delta, reg, _, _ = _registry(files, allow_approx=True)
        v = reg.register_view(ir.Aggregate(
            ir.Scan("store_sales"), ("ss_store_sk",),
            (("ss_ext_sales_price", "var", "v"),
             ("ss_ext_sales_price", "mean", "m"))), name="varv")
        assert v.kind == "incremental" and not v.exact
        delta.append_file(tpcds_data.append_rows(
            200, seed=77, n_items=400, row_group_size=1024))
        got, expect = reg.refresh(v), _oracle(reg, v)
        assert got.num_rows == expect.num_rows
        for i in (1, 2):
            np.testing.assert_allclose(
                np.asarray(force_column(got[i]).values()),
                np.asarray(force_column(expect[i]).values()),
                rtol=1e-9, atol=1e-9)
        reg.close()


# --- serving integration -----------------------------------------------------


class TestServing:
    def test_concurrent_refresh_while_serving(self):
        files = _mini_files()
        delta, reg, statics, schemas = _registry(files)
        v = reg.register_view(_cents_view_plan(), name="q3c")
        assert v.kind == "incremental"
        qfn = lower.compile_plan(v.tree,
                                 {**reg.schemas})
        base_tables = {**statics, "store_sales": delta.scan()}
        stop = threading.Event()
        errs: list = []

        def _querier():
            # keep ordinary traffic in flight while refreshes run
            while not stop.is_set():
                try:
                    sched.run("q3c", qfn, base_tables)
                except Exception as e:     # noqa: BLE001
                    errs.append(e)
                    return
        with xc.QueryScheduler(workers=2) as sched:
            th = threading.Thread(target=_querier)
            th.start()
            try:
                for e in (1, 2, 3):
                    delta.append_file(tpcds_data.append_rows(
                        12_000 // 64, seed=200 + e, n_items=400,
                        row_group_size=1024))
                    tk = sched.submit_refresh(reg, v)
                    got = tk.result()
                    _bitcmp(got, _oracle(reg, v), f"epoch{e}")
            finally:
                stop.set()
                th.join()
        assert not errs
        assert metrics.counter_value("stream.refresh.submitted") == 3
        assert metrics.counter_value("stream.refresh.incremental") == 3
        reg.close()


# --- cardinality-stats LRU (bugfix regression) -------------------------------


class TestCardinalityStatsLRU:
    def test_cap_with_read_refresh(self):
        s = plan_stats.CardinalityStats(max_entries=4)
        nodes = [ir.Scan(f"t{i}") for i in range(6)]
        for n in nodes[:4]:
            s.observe(ir.fingerprint(n), 10)
        assert len(s) == 4
        assert s.rows_for(nodes[0]) == 10.0     # read refreshes recency
        s.observe(ir.fingerprint(nodes[4]), 10)
        s.observe(ir.fingerprint(nodes[5]), 10)
        assert len(s) == 4 and s.evictions == 2
        assert s.rows_for(nodes[0]) == 10.0     # survivor: it was read
        assert s.rows_for(nodes[1]) is None     # evicted: it was not

    def test_env_cap(self, monkeypatch):
        monkeypatch.setenv("SRJT_PLAN_STATS_CAP", "2")
        s = plan_stats.CardinalityStats()
        for i in range(5):
            s.observe(ir.fingerprint(ir.Scan(f"e{i}")), i)
        assert len(s) == 2 and s.evictions == 3
