"""Flight recorder (utils/flight.py): ring-buffer bounds, concurrent
writer atomicity, probe sampling, and the incident snapshot contract —
valid JSON carrying the triggering request id, its coalesced batch
peers, and the full ring."""

import json
import os
import threading

import pytest

from spark_rapids_jni_tpu.utils import flight, metrics


@pytest.fixture(autouse=True)
def _flight_clean():
    flight.set_enabled(True)
    flight.reset()
    yield
    flight.reset()
    flight.set_capacity(int(os.environ.get("SRJT_FLIGHT_N", "512")))
    flight.set_enabled(None)


# --- ring semantics ----------------------------------------------------------


def test_ring_overflow_discards_oldest():
    flight.set_capacity(8)
    for i in range(20):
        flight.record("ev", i=i)
    evs = flight.events()
    assert len(evs) == 8
    assert [e["i"] for e in evs] == list(range(12, 20))   # newest 8, in order


def test_events_filters_by_request_id_including_batch_membership():
    flight.record("exec.submit", rid="q#1")
    flight.record("exec.submit", rid="q#2")
    flight.record("exec.batch.launch", rid="q#1", batch=["q#1", "q#2"])
    flight.record("exec.resolve", rid="q#2")
    evs = flight.events(request_id="q#2")
    # q#2's own events AND the batch launch it rode as a member
    assert [e["kind"] for e in evs] == [
        "exec.submit", "exec.batch.launch", "exec.resolve"]


def test_disabled_recorder_records_nothing():
    flight.set_enabled(False)
    flight.record("ev", i=1)
    assert flight.events() == []


def test_concurrent_writers_no_torn_records():
    flight.set_capacity(4096)
    n_threads, n_each = 6, 200
    barrier = threading.Barrier(n_threads)

    def writer(t):
        barrier.wait()
        for i in range(n_each):
            flight.record("w", thread=t, i=i, payload=f"{t}:{i}")

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    evs = flight.events()
    assert len(evs) == n_threads * n_each
    per_thread = {t: [] for t in range(n_threads)}
    for e in evs:
        # every record is whole: all fields present and mutually consistent
        assert set(e) >= {"ts", "tid", "kind", "thread", "i", "payload"}
        assert e["payload"] == f"{e['thread']}:{e['i']}"
        per_thread[e["thread"]].append(e["i"])
    # per-writer order is preserved (appends happen under the ring lock)
    for t, seq in per_thread.items():
        assert seq == list(range(n_each))


# --- probes ------------------------------------------------------------------


def test_probes_sampled_and_errors_contained():
    flight.register_probe("depth", lambda: 7)
    flight.register_probe("boom", lambda: 1 / 0)
    try:
        out = flight.sample_probes()
        assert out["depth"] == 7
        assert "probe error" in out["boom"]
    finally:
        flight.unregister_probe("depth")
        flight.unregister_probe("boom")


# --- incidents ---------------------------------------------------------------


def test_incident_snapshot_carries_rid_lifecycle_and_batch(tmp_path,
                                                           monkeypatch):
    monkeypatch.setenv("SRJT_INCIDENT_DIR", str(tmp_path))
    flight.register_probe("queue_depth", lambda: 3)
    try:
        flight.record("exec.submit", rid="q3#0")
        flight.record("exec.submit", rid="q3#1")
        flight.record("exec.coalesce", rid="q3#0", batch=["q3#0", "q3#1"])
        path = flight.incident("deadline", request_id="q3#1",
                               batch=["q3#0", "q3#1"], stage="queue")
        assert path is not None and os.path.exists(path)
        with open(path) as f:
            snap = json.load(f)             # valid JSON, not torn
        assert snap["kind"] == "deadline"
        assert snap["request_id"] == "q3#1"
        assert snap["batch"] == ["q3#0", "q3#1"]
        assert snap["fields"]["stage"] == "queue"
        assert snap["probes"]["queue_depth"] == 3
        kinds = [(e["kind"], e.get("rid")) for e in snap["events"]]
        assert ("exec.submit", "q3#1") in kinds
        assert ("exec.coalesce", "q3#0") in kinds      # batch peer linked
        assert ("incident:deadline", "q3#1") in kinds
        assert "metrics" in snap
    finally:
        flight.unregister_probe("queue_depth")


def test_incident_per_kind_cap(tmp_path, monkeypatch):
    monkeypatch.setenv("SRJT_INCIDENT_DIR", str(tmp_path))
    monkeypatch.setenv("SRJT_INCIDENT_PER_KIND", "2")
    paths = [flight.incident("storm", request_id=f"r#{i}")
             for i in range(5)]
    written = [p for p in paths if p]
    assert len(written) == 2                 # cap holds
    assert len(list(tmp_path.iterdir())) == 2
    # a different kind has its own budget
    assert flight.incident("other") is not None


def test_incident_without_dir_records_but_writes_nothing(monkeypatch):
    monkeypatch.delenv("SRJT_INCIDENT_DIR", raising=False)
    metrics.set_enabled(True)
    metrics.reset()
    try:
        assert flight.incident("quiet", request_id="r#0") is None
        assert flight.events()[-1]["kind"] == "incident:quiet"
        assert metrics.snapshot()["counters"]["flight.incidents"] == 1
    finally:
        metrics.reset()
        metrics.set_enabled(None)


def test_incident_never_raises_on_unwritable_dir(monkeypatch, tmp_path):
    bad = tmp_path / "not-a-dir"
    bad.write_text("file, not dir")
    monkeypatch.setenv("SRJT_INCIDENT_DIR", str(bad))
    assert flight.incident("doomed", request_id="r#1") is None
