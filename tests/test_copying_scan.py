"""concat/slice/scan/distinct — differential vs pandas/numpy oracles.

These fill the libcudf op-breadth gap (SURVEY §2.9: cudf::concatenate,
cudf::slice, scan, drop_duplicates) flagged in VERDICT round 1.
"""

import jax.numpy as jnp
import numpy as np
import pandas as pd
import pytest

from spark_rapids_jni_tpu import types as T
from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu import ops
from spark_rapids_jni_tpu.ops import (concat_tables, cumulative_count,
                                      cumulative_max, cumulative_min,
                                      cumulative_sum, distinct, slice_table)


def _mixed(n, seed):
    rng = np.random.default_rng(seed)
    ints = Column.from_numpy(rng.integers(-100, 100, n).astype(np.int32),
                             validity=rng.random(n) < 0.9)
    strs = Column.strings_from_list(
        [None if rng.random() < 0.1 else f"s{rng.integers(0, 50)}"
         for _ in range(n)])
    lists = Column.list_from_pylist(
        [None if rng.random() < 0.1 else
         list(rng.integers(0, 10, rng.integers(0, 4)).tolist())
         for _ in range(n)])
    return Table([ints, strs, lists])


class TestConcat:
    def test_concat_mixed(self):
        a, b, c = _mixed(17, 0), _mixed(5, 1), _mixed(31, 2)
        out = concat_tables([a, b, c])
        assert out.num_rows == 53
        for i in range(3):
            want = a[i].to_pylist() + b[i].to_pylist() + c[i].to_pylist()
            assert out[i].to_pylist() == want

    def test_concat_single(self):
        a = _mixed(4, 3)
        out = concat_tables([a])
        assert out[1].to_pylist() == a[1].to_pylist()

    def test_dtype_mismatch_rejected(self):
        a = Table([Column.from_numpy(np.zeros(2, np.int32))])
        b = Table([Column.from_numpy(np.zeros(2, np.int64))])
        with pytest.raises(TypeError):
            concat_tables([a, b])


class TestSlice:
    def test_slice_mixed(self):
        t = _mixed(40, 4)
        out = slice_table(t, 7, 11)
        assert out.num_rows == 11
        for i in range(3):
            assert out[i].to_pylist() == t[i].to_pylist()[7:18]

    def test_slice_bounds_clamped(self):
        t = _mixed(10, 5)
        assert slice_table(t, 8, 100).num_rows == 2
        assert slice_table(t, 100, 5).num_rows == 0
        assert slice_table(t, 0).num_rows == 10

    def test_slice_then_concat_roundtrip(self):
        t = _mixed(23, 6)
        parts = [slice_table(t, 0, 9), slice_table(t, 9, 9),
                 slice_table(t, 18, 9)]
        out = concat_tables(parts)
        for i in range(3):
            assert out[i].to_pylist() == t[i].to_pylist()


class TestScan:
    def test_cumsum_matches_pandas(self):
        rng = np.random.default_rng(7)
        vals = rng.integers(-50, 50, 100).astype(np.int32)
        valid = rng.random(100) < 0.8
        col = Column.from_numpy(vals, validity=valid)
        got = cumulative_sum(col)
        s = pd.Series(np.where(valid, vals, np.nan))
        want = s.fillna(0).cumsum()
        got_vals = np.asarray(got.data)
        np.testing.assert_array_equal(got_vals, want.to_numpy().astype(np.int64))
        # null positions stay null (cudf EXCLUDE policy)
        assert got.to_pylist() == [int(w) if v else None
                                   for w, v in zip(want, valid)]

    def test_cumsum_float(self):
        vals = np.asarray([1.5, 2.5, -1.0], np.float32)
        got = cumulative_sum(Column.from_numpy(vals))
        assert got.dtype == T.float64
        np.testing.assert_allclose(got.to_numpy(), [1.5, 4.0, 3.0])

    def test_cummin_cummax(self):
        rng = np.random.default_rng(8)
        vals = rng.integers(-50, 50, 64).astype(np.int64)
        valid = rng.random(64) < 0.7
        col = Column.from_numpy(vals, validity=valid)
        s = pd.Series(np.where(valid, vals.astype(float), np.nan))
        np.testing.assert_array_equal(
            np.asarray(cumulative_max(col).data)[valid],
            s.cummax().to_numpy()[valid].astype(np.int64))
        np.testing.assert_array_equal(
            np.asarray(cumulative_min(col).data)[valid],
            s.cummin().to_numpy()[valid].astype(np.int64))

    def test_cumcount(self):
        col = Column.from_numpy(np.arange(5, dtype=np.int32),
                                validity=np.asarray([1, 0, 1, 1, 0], bool))
        assert np.asarray(cumulative_count(col).data).tolist() == [1, 1, 2, 3, 3]

    def test_scan_rejects_strings(self):
        with pytest.raises(TypeError):
            cumulative_sum(Column.strings_from_list(["a"]))


class TestDistinct:
    def test_distinct_matches_pandas(self):
        rng = np.random.default_rng(9)
        a = rng.integers(0, 4, 60).astype(np.int32)
        b = [f"k{v}" for v in rng.integers(0, 3, 60)]
        t = Table([Column.from_numpy(a), Column.strings_from_list(b)])
        out = distinct(t)
        got = set(zip(out[0].to_pylist(), out[1].to_pylist()))
        want = set(pd.DataFrame({"a": a, "b": b})
                   .drop_duplicates().itertuples(index=False, name=None))
        assert got == want

    def test_distinct_empty(self):
        t = Table([Column.from_numpy(np.zeros(0, np.int32))])
        assert distinct(t).num_rows == 0


class TestReviewRegressions:
    def test_distinct_decimal128_column(self):
        from spark_rapids_jni_tpu.ops import decimal128 as d128
        col = d128.from_pyints([2**100, 5, 2**100, 5, 7])
        out = distinct(Table([col]))
        assert sorted(out[0].to_pylist()) == [5, 7, 2**100]

    def test_distinct_list_column_rejected(self):
        col = Column.list_from_pylist([[1], [1]])
        with pytest.raises(NotImplementedError):
            distinct(Table([col]))

    def test_cumsum_decimal32_widens(self):
        # running total exceeds int32: must widen to decimal64, not wrap
        vals = np.full(1100, 2_000_000_000 // 1000, np.int32) * 1000
        col = Column.from_numpy(vals, T.decimal32(-2))
        out = cumulative_sum(col)
        assert out.dtype == T.decimal64(-2)
        assert int(np.asarray(out.data)[-1]) == int(vals.astype(np.int64).sum())

    def test_cumsum_decimal128_rejected(self):
        from spark_rapids_jni_tpu.ops import decimal128 as d128
        with pytest.raises(TypeError):
            cumulative_sum(d128.from_pyints([1]))


class TestIsin:
    def test_isin_ints_vs_pandas(self):
        rng = np.random.default_rng(11)
        vals = rng.integers(0, 50, 300).astype(np.int32)
        valid = rng.random(300) < 0.9
        col = Column.from_numpy(vals, validity=valid)
        wanted = [3, 7, 49, 100]
        got = np.asarray(ops.isin(col, wanted))
        want = pd.Series(vals).isin(wanted).to_numpy() & valid
        np.testing.assert_array_equal(got, want)

    def test_isin_strings(self):
        col = Column.strings_from_list(["a", "bb", None, "c", "bb"])
        got = np.asarray(ops.isin(col, ["bb", "c"]))
        np.testing.assert_array_equal(got, [False, True, False, True, True])

    def test_isin_empty_list(self):
        col = Column.from_numpy(np.arange(4, dtype=np.int64))
        assert not np.asarray(ops.isin(col, [])).any()

    def test_isin_lossy_probes_match_nothing(self):
        col = Column.from_numpy(np.asarray([3, 4], np.int32))
        assert np.asarray(ops.isin(col, [3.5])).tolist() == [False, False]
        assert np.asarray(ops.isin(col, [3.0, None])).tolist() == [True,
                                                                   False]
        ucol = Column.from_numpy(np.asarray([1], np.uint32))
        assert np.asarray(ops.isin(ucol, [-1])).tolist() == [False]

    def test_isin_string_none_entry(self):
        col = Column.strings_from_list(["a", "b"])
        assert np.asarray(ops.isin(col, ["a", None])).tolist() == [True,
                                                                   False]
