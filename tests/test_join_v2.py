"""Join engine v2 differential tests (ops/join_plan.py).

Three contracts:
* the dense direct-lookup engine produces BIT-IDENTICAL join indices to
  the sort-probe engine on every overlapping input (null keys, duplicate
  build keys, empty build side, inner/left/semi/anti) — pinned with
  ``join_plan.force_engine``;
* the build-side index cache returns the same physical index (and thus
  identical join indices) when the same key buffers join again;
* ``join_aggregate`` fusion (unique-build, weighted, and fallback paths)
  matches the unfused ``groupby_aggregate(inner_join(...))`` exactly.
"""

import numpy as np
import pandas as pd
import pytest
import jax.numpy as jnp

import spark_rapids_jni_tpu as sr
from spark_rapids_jni_tpu import Column, Table
from spark_rapids_jni_tpu import ops
from spark_rapids_jni_tpu.ops import join_plan
from spark_rapids_jni_tpu.ops.join import join_indices

RNG = np.random.default_rng(42)


def int_col(vals, validity=None, dt=None):
    return Column.from_numpy(np.asarray(vals), dt, validity)


def _both_engines(left, right, how):
    with join_plan.force_engine("dense"):
        d = join_indices(left, right, how)
    with join_plan.force_engine("sorted"):
        s = join_indices(left, right, how)
    return d, s


def _assert_same(d, s):
    if isinstance(d, tuple):
        for a, b in zip(d, s):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    else:
        np.testing.assert_array_equal(np.asarray(d), np.asarray(s))


@pytest.mark.parametrize("how", ["inner", "left", "semi", "anti"])
def test_dense_matches_sorted_random(how):
    lk = RNG.integers(0, 400, 3000, dtype=np.int64)
    rk = RNG.integers(0, 400, 500, dtype=np.int64)   # duplicate build keys
    d, s = _both_engines(int_col(lk), int_col(rk), how)
    _assert_same(d, s)


@pytest.mark.parametrize("how", ["inner", "left", "semi", "anti"])
def test_dense_matches_sorted_null_keys(how):
    lk = RNG.integers(0, 50, 600, dtype=np.int64)
    rk = RNG.integers(0, 50, 200, dtype=np.int64)
    lv = RNG.random(600) < 0.85
    rv = RNG.random(200) < 0.85
    d, s = _both_engines(int_col(lk, validity=lv), int_col(rk, validity=rv),
                         how)
    _assert_same(d, s)


@pytest.mark.parametrize("how", ["inner", "left", "semi", "anti"])
def test_dense_matches_sorted_unique_build(how):
    # unique build keys take the scatter-built no-sort index and the
    # no-expansion probe tail — the TPC-DS star shape
    rk = RNG.permutation(np.arange(1000, 2000, dtype=np.int64))[:700]
    lk = np.where(RNG.random(4000) < 0.8,
                  rk[RNG.integers(0, 700, 4000)],
                  RNG.integers(5000, 6000, 4000)).astype(np.int64)
    d, s = _both_engines(int_col(lk), int_col(rk), how)
    _assert_same(d, s)


@pytest.mark.parametrize("how", ["inner", "left", "semi", "anti"])
def test_dense_matches_sorted_empty_build(how):
    lk = np.asarray([1, 2, 3], dtype=np.int64)
    rk = np.zeros(0, dtype=np.int64)
    d, s = _both_engines(int_col(lk), int_col(rk), how)
    _assert_same(d, s)


def test_dense_inner_join_vs_pandas():
    nl, nr = 2000, 300
    lk = RNG.integers(0, 120, nl, dtype=np.int64)
    rk = RNG.integers(0, 120, nr, dtype=np.int64)
    lv = np.arange(nl, dtype=np.int32)
    rv = np.arange(nr, dtype=np.int32) + 7000
    with join_plan.force_engine("dense"):
        out = ops.inner_join(Table([int_col(lk), int_col(lv)]),
                             Table([int_col(rk), int_col(rv)]), 0, 0)
    got = sorted(zip(out[0].to_pylist(), out[1].to_pylist(),
                     out[3].to_pylist()))
    df = pd.merge(pd.DataFrame({"k": lk, "lv": lv}),
                  pd.DataFrame({"k": rk, "rv": rv}), on="k")
    assert got == sorted(zip(df["k"], df["lv"], df["rv"]))


def test_planner_picks_dense_for_dense_keys_only():
    dense = jnp.asarray(np.arange(100, 1100, dtype=np.int64))
    sparse = jnp.asarray(
        RNG.integers(0, 2**60, 1000, dtype=np.int64))
    assert join_plan.build_index(dense, None, True).kind == "dense"
    assert join_plan.build_index(sparse, None, True).kind == "sorted"
    # ineligible dtypes never go dense, regardless of span
    f = Column.from_numpy(np.asarray([1.0, 2.0]))
    assert not join_plan.dense_eligible(f)
    u = Column.from_numpy(np.asarray([1, 2], dtype=np.uint64))
    assert not join_plan.dense_eligible(u)
    i = Column.from_numpy(np.asarray([1, 2], dtype=np.int32))
    assert join_plan.dense_eligible(i)


def test_build_index_cache_hit_returns_identical_index():
    data = jnp.asarray(np.arange(10, 500, dtype=np.int64))
    ix1 = join_plan.build_index(data, None, True)
    ix2 = join_plan.build_index(data, None, True)
    assert ix1 is ix2                      # memoized on buffer identity
    # a distinct buffer with equal contents is a different build side
    data2 = jnp.asarray(np.arange(10, 500, dtype=np.int64))
    assert join_plan.build_index(data2, None, True) is not ix1


def test_cache_hit_join_indices_identical():
    rt = int_col(RNG.permutation(np.arange(300, dtype=np.int64)))
    lt = int_col(RNG.integers(0, 300, 2000, dtype=np.int64))
    li1, ri1 = join_indices(lt, rt, "inner")
    li2, ri2 = join_indices(lt, rt, "inner")   # build index from cache
    np.testing.assert_array_equal(np.asarray(li1), np.asarray(li2))
    np.testing.assert_array_equal(np.asarray(ri1), np.asarray(ri2))


def test_forced_engine_env_var(monkeypatch):
    monkeypatch.setenv("SRJT_JOIN_ENGINE", "sorted")
    dense = jnp.asarray(np.arange(0, 256, dtype=np.int64))
    assert join_plan.build_index(dense, None, True).kind == "sorted"
    monkeypatch.setenv("SRJT_JOIN_ENGINE", "bogus")   # ignored
    assert join_plan.forced_engine() is None


# ---- join→aggregate fusion -------------------------------------------------


def _fused_vs_unfused(lt, rt, left_on, right_on, keys, aggs):
    fused = ops.join_aggregate(lt, rt, left_on, right_on, keys, aggs)
    j = ops.inner_join(lt, rt, left_on, right_on)
    ref = ops.groupby_aggregate(j, keys, aggs)
    ks = list(range(len(keys)))
    fused = ops.sort_table(fused, ks)
    ref = ops.sort_table(ref, ks)
    assert fused.num_rows == ref.num_rows
    assert fused.num_columns == ref.num_columns
    for i in range(ref.num_columns):
        assert fused[i].to_pylist() == ref[i].to_pylist()


def test_fused_unique_build_all_aggs():
    # star shape: unique dimension PK, group by a dimension attribute
    n, nd = 5000, 400
    dim_sk = np.arange(10, 10 + nd, dtype=np.int64)
    dim_cat = RNG.integers(0, 9, nd, dtype=np.int64)
    fk = np.where(RNG.random(n) < 0.9, dim_sk[RNG.integers(0, nd, n)],
                  RNG.integers(9000, 9500, n)).astype(np.int64)
    val = RNG.integers(-50, 50, n, dtype=np.int64)
    vv = RNG.random(n) < 0.9
    lt = Table([int_col(fk), int_col(val, validity=vv)])
    rt = Table([int_col(dim_sk), int_col(dim_cat)])
    _fused_vs_unfused(lt, rt, 0, 0, [3],
                      [(1, "sum"), (1, "count"), (1, "mean"),
                       (1, "min"), (1, "max")])


def test_fused_unique_build_left_side_keys():
    n, nd = 3000, 256
    dim_sk = np.arange(0, nd, dtype=np.int64)
    fk = dim_sk[RNG.integers(0, nd, n)].astype(np.int64)
    grp = RNG.integers(0, 6, n, dtype=np.int64)
    val = RNG.integers(0, 100, n, dtype=np.int64)
    lt = Table([int_col(fk), int_col(grp), int_col(val)])
    rt = Table([int_col(dim_sk)])
    _fused_vs_unfused(lt, rt, 0, 0, [1], [(2, "sum"), (2, "mean")])


def test_fused_weighted_duplicate_build():
    # duplicate build keys + probe-side-only keys/values → weighted path
    n, nb = 2500, 300
    base = np.arange(50, 150, dtype=np.int64)
    bk = base[RNG.integers(0, 100, nb)].astype(np.int64)
    fk = np.where(RNG.random(n) < 0.8, base[RNG.integers(0, 100, n)],
                  RNG.integers(700, 900, n)).astype(np.int64)
    grp = RNG.integers(0, 5, n, dtype=np.int64)
    val = RNG.integers(-9, 9, n, dtype=np.int64)
    vv = RNG.random(n) < 0.85
    lt = Table([int_col(fk), int_col(grp), int_col(val, validity=vv)])
    rt = Table([int_col(bk)])
    _fused_vs_unfused(lt, rt, 0, 0, [1],
                      [(2, "sum"), (2, "count"), (2, "mean"),
                       (2, "min"), (2, "max")])


def test_fused_fallback_right_side_keys_duplicate_build():
    # duplicate build + RIGHT-side group key → materialized fallback
    n, nb = 800, 120
    base = np.arange(0, 40, dtype=np.int64)
    bk = base[RNG.integers(0, 40, nb)].astype(np.int64)
    bg = RNG.integers(0, 4, nb, dtype=np.int64)
    fk = base[RNG.integers(0, 40, n)].astype(np.int64)
    val = RNG.integers(0, 20, n, dtype=np.int64)
    lt = Table([int_col(fk), int_col(val)])
    rt = Table([int_col(bk), int_col(bg)])
    _fused_vs_unfused(lt, rt, 0, 0, [3], [(1, "sum")])


def test_fused_string_group_key_unique_build():
    nd = 64
    dim_sk = np.arange(0, nd, dtype=np.int64)
    cats = Column.strings_from_list([f"cat{i % 7}" for i in range(nd)])
    fk = dim_sk[RNG.integers(0, nd, 1500)].astype(np.int64)
    val = RNG.integers(0, 30, 1500, dtype=np.int64)
    lt = Table([int_col(fk), int_col(val)])
    rt = Table([int_col(dim_sk), cats])
    _fused_vs_unfused(lt, rt, 0, 0, [3], [(1, "sum"), (1, "count")])


def test_fused_empty_probe():
    lt = Table([int_col(np.zeros(0, np.int64)),
                int_col(np.zeros(0, np.int64))])
    rt = Table([int_col(np.arange(5, dtype=np.int64))])
    out = ops.join_aggregate(lt, rt, 0, 0, [0], [(1, "sum")])
    assert out.num_rows == 0


def test_fused_under_capture_replay():
    # the fused dense path must compile: planner scalars ride the tape and
    # the build-index memo is disabled so capture and replay stay aligned
    from spark_rapids_jni_tpu.models.compiled import compile_query

    nd, n = 128, 2000
    dim_sk = np.arange(0, nd, dtype=np.int64)
    dim_cat = RNG.integers(0, 5, nd, dtype=np.int64)
    fk = dim_sk[RNG.integers(0, nd, n)].astype(np.int64)
    val = RNG.integers(0, 40, n, dtype=np.int64)
    tables = {
        "fact": Table([int_col(fk), int_col(val)]),
        "dim": Table([int_col(dim_sk), int_col(dim_cat)]),
    }

    def q(t):
        out = ops.join_aggregate(t["fact"], t["dim"], 0, 0, [3],
                                 [(1, "sum")])
        return ops.sort_table(out, [0])

    eager = q(tables)
    cq = compile_query(q, tables)
    out = cq.run(tables)
    assert out[0].to_pylist() == eager[0].to_pylist()
    assert out[1].to_pylist() == eager[1].to_pylist()


# ---- distributed dense shard probe ----------------------------------------


def test_repartition_dense_spec_matches_sorted():
    from spark_rapids_jni_tpu.parallel import make_mesh
    from spark_rapids_jni_tpu.parallel.repartition_join import (
        JoinAggSpec, repartition_join_agg)

    mesh = make_mesh(8, "data")
    rng = np.random.default_rng(7)
    n_fact, n_item, n_cat = 2048, 256, 6
    base = np.arange(100, 200, dtype=np.int64)
    item_sk = base[rng.integers(0, 100, n_item)].astype(np.int64)
    item_cat = rng.integers(0, n_cat, n_item).astype(np.int32)
    fact_sk = np.where(rng.random(n_fact) < 0.8,
                       base[rng.integers(0, 100, n_fact)],
                       rng.integers(700, 900, n_fact)).astype(np.int64)
    fact_qty = rng.integers(1, 30, n_fact).astype(np.int64)
    fv = np.ones((n_fact, 2), bool)
    iv = np.ones((n_item, 2), bool)
    fv[:, 0] = rng.random(n_fact) < 0.9
    iv[:, 0] = rng.random(n_item) < 0.9

    common = dict(fact_schema=(sr.int64, sr.int64),
                  build_schema=(sr.int64, sr.int32),
                  fact_key_idx=0, build_key_idx=0, build_group_idx=1,
                  fact_value_idx=1, num_groups=n_cat,
                  fact_capacity=n_fact, build_capacity=n_item)
    args = ((jnp.asarray(fact_sk), jnp.asarray(fact_qty)), jnp.asarray(fv),
            (jnp.asarray(item_sk), jnp.asarray(item_cat)), jnp.asarray(iv))
    # dense window deliberately wider than the key range (offset base)
    dense = JoinAggSpec(**common, key_min=64, key_span=1024)
    sorted_ = JoinAggSpec(**common)
    ds, dc, dd = repartition_join_agg(mesh, dense, *args)
    ss_, sc, sd = repartition_join_agg(mesh, sorted_, *args)
    assert int(np.asarray(dd)) == 0 and int(np.asarray(sd)) == 0
    np.testing.assert_array_equal(np.asarray(ds), np.asarray(ss_))
    np.testing.assert_array_equal(np.asarray(dc), np.asarray(sc))


# ---- multi-column keys: composite / fingerprint / fallback -----------------


def _py_pairs(lcols, rcols, how):
    """Reference multi-key equi-join on host tuples: a null in ANY key
    column never matches; matches enumerate in build-row order (the
    engines' stable key-sorted tie order)."""
    nl, nr = len(lcols[0][0]), len(rcols[0][0])
    rmap = {}
    for j in range(nr):
        if any(v is not None and not v[j] for _, v in rcols):
            continue
        rmap.setdefault(tuple(a[j] for a, _ in rcols), []).append(j)
    out = []
    for i in range(nl):
        null = any(v is not None and not v[i] for _, v in lcols)
        matches = [] if null else rmap.get(tuple(a[i] for a, _ in lcols), [])
        if how == "inner":
            out += [(i, j) for j in matches]
        elif how == "left":
            out += [(i, j) for j in matches] or [(i, -1)]
        elif how == "semi":
            out += [i] if matches else []
        else:
            out += [] if matches else [i]
    return out


def _got_pairs(res, how):
    if how in ("semi", "anti"):
        return np.asarray(res).tolist()
    li, ri = res
    return list(zip(np.asarray(li).tolist(), np.asarray(ri).tolist()))


@pytest.mark.parametrize("how", ["inner", "left", "semi", "anti"])
def test_composite_2key_engines_and_oracle(how):
    n, m = 1500, 400
    la = RNG.integers(0, 40, n, dtype=np.int64)
    lb = RNG.integers(0, 30, n).astype(np.int32)    # mixed key widths
    ra = RNG.integers(0, 40, m, dtype=np.int64)
    rb = RNG.integers(0, 30, m).astype(np.int32)
    lv = RNG.random(n) < 0.9
    rv = RNG.random(m) < 0.9
    lt = [int_col(la, validity=lv), int_col(lb)]
    rt = [int_col(ra), int_col(rb, validity=rv)]
    plan = join_plan.plan_keys(lt, rt)
    assert plan.mode == "composite" and plan.dense_ok and not plan.verify
    d, s = _both_engines(lt, rt, how)
    _assert_same(d, s)
    ref = _py_pairs([(la, lv), (lb, None)], [(ra, None), (rb, rv)], how)
    assert _got_pairs(d, how) == ref


def test_composite_3key_vs_pandas():
    n, m = 2000, 500
    lk = [RNG.integers(0, 12, n, dtype=np.int64) for _ in range(3)]
    rk = [RNG.integers(0, 12, m, dtype=np.int64) for _ in range(3)]
    lv = RNG.random(n) < 0.92
    lt = [int_col(lk[0], validity=lv), int_col(lk[1]), int_col(lk[2])]
    rt = [int_col(rk[0]), int_col(rk[1]), int_col(rk[2])]
    assert join_plan.plan_keys(lt, rt).mode == "composite"
    # null keys → per-row sentinels outside the key range, so a plain
    # pandas merge reproduces SQL null-never-matches semantics
    a = lk[0].copy()
    a[~lv] = -1000 - np.arange(np.count_nonzero(~lv))
    ldf = pd.DataFrame({"a": a, "b": lk[1], "c": lk[2], "li": np.arange(n)})
    rdf = pd.DataFrame({"a": rk[0], "b": rk[1], "c": rk[2],
                        "rj": np.arange(m)})
    for how in ("inner", "left"):
        li, ri = join_indices(lt, rt, how)
        mg = ldf.merge(rdf, on=["a", "b", "c"], how=how)
        exp = sorted(zip(mg["li"].tolist(),
                         mg["rj"].fillna(-1).astype(int).tolist()))
        assert sorted(_got_pairs((li, ri), how)) == exp


def test_composite_string_int_key():
    cats = [f"s{i}" for i in range(9)]
    n, m = 1200, 300
    ls = [cats[i] for i in RNG.integers(0, 9, n)]
    rs = [cats[i] for i in RNG.integers(0, 9, m)]
    lb = RNG.integers(0, 25, n, dtype=np.int64)
    rb = RNG.integers(0, 25, m, dtype=np.int64)
    lt = [Column.strings_from_list(ls), int_col(lb)]
    rt = [Column.strings_from_list(rs), int_col(rb)]
    # dictionary codes from the shared encode are dense-eligible → packed
    assert join_plan.plan_keys(lt, rt).mode == "composite"
    li, ri = join_indices(lt, rt, "inner")
    got = sorted((ls[i], int(lb[i]), int(rb[j]))
                 for i, j in _got_pairs((li, ri), "inner"))
    df = pd.merge(pd.DataFrame({"s": ls, "b": lb}),
                  pd.DataFrame({"s": rs, "b": rb}), on=["s", "b"])
    assert got == sorted(zip(df["s"], df["b"], df["b"]))


@pytest.mark.parametrize("how", ["inner", "left", "semi", "anti"])
def test_fingerprint_overflow_matches_oracle(how):
    # two wide-window int64 keys: span product overflows 63 bits → the
    # planner probes on a murmur3 fingerprint and verifies tuple equality
    n, m = 900, 250
    base = RNG.integers(-2**61, 2**61, 60, dtype=np.int64)
    la, ra = base[RNG.integers(0, 60, n)], base[RNG.integers(0, 60, m)]
    lb, rb = base[RNG.integers(0, 60, n)], base[RNG.integers(0, 60, m)]
    lv = RNG.random(n) < 0.9
    lt = [int_col(la, validity=lv), int_col(lb)]
    rt = [int_col(ra), int_col(rb)]
    plan = join_plan.plan_keys(lt, rt)
    assert plan.mode == "fingerprint" and plan.verify and not plan.dense_ok
    got = _got_pairs(join_indices(lt, rt, how), how)
    ref = _py_pairs([(la, lv), (lb, None)], [(ra, None), (rb, None)], how)
    assert sorted(got) == sorted(ref)
    if how == "left":   # engine emits probe-row-major order, like expansion
        assert got == ref


def test_fallback_f64_key_matches_oracle():
    # an f64 lane can never pack exactly → hashed probe, counted "fallback"
    n, m = 800, 200
    lf = (RNG.integers(0, 20, n) / 4.0).astype(np.float64)
    rf = (RNG.integers(0, 20, m) / 4.0).astype(np.float64)
    lb = RNG.integers(0, 10, n, dtype=np.int64)
    rb = RNG.integers(0, 10, m, dtype=np.int64)
    lt = [Column.from_numpy(lf), int_col(lb)]
    rt = [Column.from_numpy(rf), int_col(rb)]
    plan = join_plan.plan_keys(lt, rt)
    assert plan.mode == "fallback" and plan.verify
    got = _got_pairs(join_indices(lt, rt, "inner"), "inner")
    ref = _py_pairs([(lf, None), (lb, None)], [(rf, None), (rb, None)],
                    "inner")
    assert sorted(got) == sorted(ref)


def test_fingerprint_collisions_are_rejected(monkeypatch):
    # cripple the fingerprint to 5 buckets: every probe drowns in
    # collisions, the verification pass must still reject them all
    from spark_rapids_jni_tpu.ops import hashing

    monkeypatch.setattr(
        hashing, "fingerprint64",
        lambda lanes: (lanes[0].astype(jnp.int64) % 5 + 5) % 5)
    n, m = 400, 120
    la = RNG.integers(-2**61, 2**61, n, dtype=np.int64)
    ra = np.concatenate([la[RNG.integers(0, n, 60)],
                         RNG.integers(-2**61, 2**61, m - 60, dtype=np.int64)])
    lb = RNG.integers(0, 4, n, dtype=np.int64)
    rb = RNG.integers(0, 4, m, dtype=np.int64)
    lt = [int_col(la), int_col(lb)]
    rt = [int_col(ra), int_col(rb)]
    for how in ("inner", "left", "semi", "anti"):
        got = _got_pairs(join_indices(lt, rt, how), how)
        ref = _py_pairs([(la, None), (lb, None)], [(ra, None), (rb, None)],
                        how)
        assert sorted(got) == sorted(ref)


def test_single_key_list_equals_scalar_key():
    lk = int_col(RNG.integers(0, 90, 700, dtype=np.int64))
    rk = int_col(RNG.integers(0, 90, 200, dtype=np.int64))
    _assert_same(join_indices([lk], [rk], "inner"),
                 join_indices(lk, rk, "inner"))
    assert join_plan.plan_keys([lk], [rk]).mode == "single"


def test_multikey_pack_counters_and_cache_hits():
    from spark_rapids_jni_tpu.utils import metrics

    metrics.set_enabled(True)
    metrics.reset()
    try:
        lt = [int_col(RNG.integers(0, 50, 1000, dtype=np.int64)),
              int_col(RNG.integers(0, 20, 1000, dtype=np.int64))]
        rt = [int_col(RNG.integers(0, 50, 300, dtype=np.int64)),
              int_col(RNG.integers(0, 20, 300, dtype=np.int64))]
        a = join_indices(lt, rt, "inner")
        b = join_indices(lt, rt, "inner")   # same buffers → both caches hit
        _assert_same(a, b)
        c = metrics.snapshot()["counters"]
        assert c["join.pack.composite"] == 1
        assert c["join.pack.cache_hit"] >= 1
        assert c["join.build_index.cache_hit"] >= 1
    finally:
        metrics.reset()
        metrics.set_enabled(None)


# ---- left-outer join→aggregate fusion --------------------------------------


def _fused_vs_unfused_how(lt, rt, left_on, right_on, keys, aggs, how):
    fused = ops.join_aggregate(lt, rt, left_on, right_on, keys, aggs,
                               how=how)
    j = (ops.inner_join if how == "inner" else ops.left_join)(
        lt, rt, left_on, right_on)
    ref = ops.groupby_aggregate(j, keys, aggs)
    ks = list(range(len(keys)))
    fused = ops.sort_table(fused, ks)
    ref = ops.sort_table(ref, ks)
    assert fused.num_rows == ref.num_rows
    for i in range(ref.num_columns):
        assert fused[i].to_pylist() == ref[i].to_pylist()


def test_fused_left_unique_build():
    # unmatched probe rows keep null build columns — incl. the null group
    n, nd = 3000, 300
    dim_sk = np.arange(10, 10 + nd, dtype=np.int64)
    dim_cat = RNG.integers(0, 7, nd, dtype=np.int64)
    fk = np.where(RNG.random(n) < 0.8, dim_sk[RNG.integers(0, nd, n)],
                  RNG.integers(9000, 9500, n)).astype(np.int64)
    val = RNG.integers(-40, 40, n, dtype=np.int64)
    vv = RNG.random(n) < 0.9
    lt = Table([int_col(fk), int_col(val, validity=vv)])
    rt = Table([int_col(dim_sk), int_col(dim_cat)])
    _fused_vs_unfused_how(lt, rt, 0, 0, [3],
                          [(1, "sum"), (1, "count"), (1, "mean"),
                           (1, "min"), (1, "max")], "left")


def test_fused_left_weighted_duplicate_build():
    # unmatched rows weight 1 (their single null-extended joined row)
    n, nb = 2000, 250
    base = np.arange(0, 80, dtype=np.int64)
    bk = base[RNG.integers(0, 80, nb)].astype(np.int64)
    fk = np.where(RNG.random(n) < 0.7, base[RNG.integers(0, 80, n)],
                  RNG.integers(500, 700, n)).astype(np.int64)
    grp = RNG.integers(0, 5, n, dtype=np.int64)
    val = RNG.integers(-9, 9, n, dtype=np.int64)
    vv = RNG.random(n) < 0.85
    lt = Table([int_col(fk), int_col(grp), int_col(val, validity=vv)])
    rt = Table([int_col(bk)])
    _fused_vs_unfused_how(lt, rt, 0, 0, [1],
                          [(2, "sum"), (2, "count"), (2, "mean"),
                           (2, "min"), (2, "max")], "left")


def test_fused_multikey_composite_inner_and_left():
    n, nd = 2500, 160
    da = np.repeat(np.arange(40, dtype=np.int64), 4)
    db = np.tile(np.arange(4, dtype=np.int64), 40)      # unique (a, b) pairs
    dcat = RNG.integers(0, 6, nd, dtype=np.int64)
    fa = np.where(RNG.random(n) < 0.85, RNG.integers(0, 40, n),
                  RNG.integers(90, 120, n)).astype(np.int64)
    fb = RNG.integers(0, 4, n, dtype=np.int64)
    val = RNG.integers(0, 50, n, dtype=np.int64)
    lt = Table([int_col(fa), int_col(fb), int_col(val)])
    rt = Table([int_col(da), int_col(db), int_col(dcat)])
    for how in ("inner", "left"):
        _fused_vs_unfused_how(lt, rt, [0, 1], [0, 1], [5],
                              [(2, "sum"), (2, "count")], how)


def test_fused_fingerprint_falls_back_to_join():
    # hashed probe counts are candidate counts — fusion must not trust them
    n, m = 600, 100
    base = RNG.integers(-2**61, 2**61, 50, dtype=np.int64)
    fa, fb = base[RNG.integers(0, 50, n)], base[RNG.integers(0, 50, n)]
    ba, bb = base[RNG.integers(0, 50, m)], base[RNG.integers(0, 50, m)]
    grp = RNG.integers(0, 4, n, dtype=np.int64)
    val = RNG.integers(0, 9, n, dtype=np.int64)
    lt = Table([int_col(fa), int_col(fb), int_col(grp), int_col(val)])
    rt = Table([int_col(ba), int_col(bb)])
    for how in ("inner", "left"):
        _fused_vs_unfused_how(lt, rt, [0, 1], [0, 1], [2],
                              [(3, "sum"), (3, "count")], how)


def test_decimal128_single_key_fingerprint_verify_path():
    """A lone decimal128 key must route through the hashed
    fingerprint-and-verify pack (its (n, 2) limb storage has no single
    probe lane for the sort-probe engine) and produce the same indices
    as an int64 key with identical equality structure."""
    from spark_rapids_jni_tpu.ops import decimal128 as d128

    # mirror: same positions match in both keyings; the >64-bit values
    # force real two-limb equality through the verify lanes
    lmap = {0: 3, 1: 1, 2: 2, 3: 3, 4: 5, 5: 2**70, 6: -2**70, 7: 7}
    rmap = {0: 2, 1: 3, 2: 5, 3: 2**70, 4: 9, 5: -2**70}
    lc = d128.from_pyints([lmap[i] for i in range(8)], scale=0)
    rc = d128.from_pyints([rmap[i] for i in range(6)], scale=0)
    small = {2**70: 100, -2**70: -100}
    li = int_col(np.asarray([small.get(lmap[i], lmap[i])
                             for i in range(8)], np.int64))
    ri = int_col(np.asarray([small.get(rmap[i], rmap[i])
                             for i in range(6)], np.int64))

    plan = join_plan.plan_keys([lc], [rc])
    assert plan.mode == "fallback"
    assert plan.ldata.ndim == 1 and len(plan.verify) == 2

    for how in ("inner", "left"):
        dl, dr = join_indices(lc, rc, how)
        il, ir_ = join_indices(li, ri, how)
        assert sorted(zip(np.asarray(dl).tolist(),
                          np.asarray(dr).tolist())) \
            == sorted(zip(np.asarray(il).tolist(),
                          np.asarray(ir_).tolist()))
    for how in ("semi", "anti"):
        assert np.asarray(join_indices(lc, rc, how)).tolist() \
            == np.asarray(join_indices(li, ri, how)).tolist()


def test_decimal128_key_with_nulls_and_collision_scale():
    """Nulls never match, and same-low-limb values differing only in the
    high limb (fingerprint collision bait) are kept apart by the verify
    lanes."""
    from spark_rapids_jni_tpu.ops import decimal128 as d128

    # low limbs equal, high limbs differ: v and v + 2**64
    lv = [5, 5 + 2**64, None, 9]
    rv = [5, 9, None, 5 + 2**64]
    lc = d128.from_pyints(lv, scale=0)
    rc = d128.from_pyints(rv, scale=0)
    dl, dr = join_indices(lc, rc, "inner")
    pairs = sorted(zip(np.asarray(dl).tolist(), np.asarray(dr).tolist()))
    expect = sorted((i, j) for i, a in enumerate(lv)
                    for j, b in enumerate(rv)
                    if a is not None and b is not None and a == b)
    assert pairs == expect
