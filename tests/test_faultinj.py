"""Fault-injection shim tests (tier-3 resilience tooling, SURVEY §2.6/§4)."""

import json
import os
import time

import numpy as np
import pytest

from spark_rapids_jni_tpu import Column, Table, convert_to_rows
from spark_rapids_jni_tpu import faultinj
from spark_rapids_jni_tpu.faultinj.injector import (InjectedDeviceError,
                                                    InjectedOomError,
                                                    FaultInjector)


@pytest.fixture(autouse=True)
def clean_injector():
    yield
    faultinj.disable()


def write_cfg(tmp_path, cfg):
    p = tmp_path / "faultinj.json"
    p.write_text(json.dumps(cfg))
    return str(p)


def small_table():
    return Table([Column.from_numpy(np.arange(10, dtype=np.int64))])


def test_injects_on_named_site(tmp_path):
    faultinj.enable(write_cfg(tmp_path, {
        "sites": {"convert_to_rows": {"percent": 100,
                                      "injectionType": "device_error"}}}))
    with pytest.raises(InjectedDeviceError, match="convert_to_rows"):
        convert_to_rows(small_table())


def test_untargeted_site_unaffected(tmp_path):
    faultinj.enable(write_cfg(tmp_path, {
        "sites": {"parquet_read_table": {"percent": 100}}}))
    assert len(convert_to_rows(small_table())) == 1   # unaffected


def test_wildcard_matches_everything(tmp_path):
    faultinj.enable(write_cfg(tmp_path, {
        "sites": {"*": {"percent": 100, "injectionType": "oom"}}}))
    with pytest.raises(InjectedOomError):
        convert_to_rows(small_table())


def test_interception_count_budget(tmp_path):
    faultinj.enable(write_cfg(tmp_path, {
        "sites": {"convert_to_rows": {"percent": 100,
                                      "interceptionCount": 2}}}))
    for _ in range(2):
        with pytest.raises(InjectedDeviceError):
            convert_to_rows(small_table())
    # budget exhausted → calls succeed again
    assert len(convert_to_rows(small_table())) == 1
    assert faultinj.get_injector().injected_count == 2


def test_percent_dice_seeded(tmp_path):
    faultinj.enable(write_cfg(tmp_path, {
        "seed": 7,
        "sites": {"convert_to_rows": {"percent": 50}}}))
    outcomes = []
    for _ in range(40):
        try:
            convert_to_rows(small_table())
            outcomes.append(False)
        except InjectedDeviceError:
            outcomes.append(True)
    hits = sum(outcomes)
    assert 5 < hits < 35   # ~50% with seeded dice


def test_substitute_result(tmp_path):
    faultinj.enable(write_cfg(tmp_path, {
        "sites": {"convert_to_rows": {"percent": 100,
                                      "injectionType": "substitute",
                                      "substituteResult": []}}}))
    assert convert_to_rows(small_table()) == []


def test_hot_reload(tmp_path):
    path = write_cfg(tmp_path, {"dynamic": True, "sites": {}})
    faultinj.enable(path)
    assert len(convert_to_rows(small_table())) == 1
    # rewrite config; watcher polls every 250ms
    time.sleep(0.05)
    with open(path, "w") as f:
        json.dump({"dynamic": True,
                   "sites": {"convert_to_rows": {"percent": 100}}}, f)
    os.utime(path)
    deadline = time.time() + 5
    fired = False
    while time.time() < deadline:
        try:
            convert_to_rows(small_table())
        except InjectedDeviceError:
            fired = True
            break
        time.sleep(0.1)
    assert fired, "hot reload did not pick up the new config"


def test_env_var_config(tmp_path, monkeypatch):
    path = write_cfg(tmp_path, {
        "sites": {"convert_to_rows": {"percent": 100}}})
    monkeypatch.setenv("FAULT_INJECTOR_CONFIG_PATH", path)
    faultinj.enable()   # picks the path from the env, like the reference
    with pytest.raises(InjectedDeviceError):
        convert_to_rows(small_table())


def test_bad_config_rejected(tmp_path):
    inj = FaultInjector()
    p = tmp_path / "bad.json"
    p.write_text(json.dumps({"sites": {"x": {"injectionType": "nope"}}}))
    with pytest.raises(ValueError, match="injectionType"):
        inj.load_config(str(p))
