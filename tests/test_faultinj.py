"""Fault-injection shim tests (tier-3 resilience tooling, SURVEY §2.6/§4)."""

import json
import os
import time

import numpy as np
import pytest

from spark_rapids_jni_tpu import Column, Table, convert_to_rows
from spark_rapids_jni_tpu import faultinj
from spark_rapids_jni_tpu.faultinj.injector import (InjectedDeviceError,
                                                    InjectedOomError,
                                                    FaultInjector)


@pytest.fixture(autouse=True)
def clean_injector():
    yield
    faultinj.disable()


def write_cfg(tmp_path, cfg):
    p = tmp_path / "faultinj.json"
    p.write_text(json.dumps(cfg))
    return str(p)


def small_table():
    return Table([Column.from_numpy(np.arange(10, dtype=np.int64))])


def test_injects_on_named_site(tmp_path):
    faultinj.enable(write_cfg(tmp_path, {
        "sites": {"convert_to_rows": {"percent": 100,
                                      "injectionType": "device_error"}}}))
    with pytest.raises(InjectedDeviceError, match="convert_to_rows"):
        convert_to_rows(small_table())


def test_untargeted_site_unaffected(tmp_path):
    faultinj.enable(write_cfg(tmp_path, {
        "sites": {"parquet_read_table": {"percent": 100}}}))
    assert len(convert_to_rows(small_table())) == 1   # unaffected


def test_wildcard_matches_everything(tmp_path):
    faultinj.enable(write_cfg(tmp_path, {
        "sites": {"*": {"percent": 100, "injectionType": "oom"}}}))
    with pytest.raises(InjectedOomError):
        convert_to_rows(small_table())


def test_interception_count_budget(tmp_path):
    faultinj.enable(write_cfg(tmp_path, {
        "sites": {"convert_to_rows": {"percent": 100,
                                      "interceptionCount": 2}}}))
    for _ in range(2):
        with pytest.raises(InjectedDeviceError):
            convert_to_rows(small_table())
    # budget exhausted → calls succeed again
    assert len(convert_to_rows(small_table())) == 1
    assert faultinj.get_injector().injected_count == 2


def test_percent_dice_seeded(tmp_path):
    faultinj.enable(write_cfg(tmp_path, {
        "seed": 7,
        "sites": {"convert_to_rows": {"percent": 50}}}))
    outcomes = []
    for _ in range(40):
        try:
            convert_to_rows(small_table())
            outcomes.append(False)
        except InjectedDeviceError:
            outcomes.append(True)
    hits = sum(outcomes)
    assert 5 < hits < 35   # ~50% with seeded dice


def test_substitute_result(tmp_path):
    faultinj.enable(write_cfg(tmp_path, {
        "sites": {"convert_to_rows": {"percent": 100,
                                      "injectionType": "substitute",
                                      "substituteResult": []}}}))
    assert convert_to_rows(small_table()) == []


def test_hot_reload(tmp_path):
    path = write_cfg(tmp_path, {"dynamic": True, "sites": {}})
    faultinj.enable(path)
    assert len(convert_to_rows(small_table())) == 1
    # rewrite config; watcher polls every 250ms
    time.sleep(0.05)
    with open(path, "w") as f:
        json.dump({"dynamic": True,
                   "sites": {"convert_to_rows": {"percent": 100}}}, f)
    os.utime(path)
    deadline = time.time() + 5
    fired = False
    while time.time() < deadline:
        try:
            convert_to_rows(small_table())
        except InjectedDeviceError:
            fired = True
            break
        time.sleep(0.1)
    assert fired, "hot reload did not pick up the new config"


def test_env_var_config(tmp_path, monkeypatch):
    path = write_cfg(tmp_path, {
        "sites": {"convert_to_rows": {"percent": 100}}})
    monkeypatch.setenv("FAULT_INJECTOR_CONFIG_PATH", path)
    faultinj.enable()   # picks the path from the env, like the reference
    with pytest.raises(InjectedDeviceError):
        convert_to_rows(small_table())


def test_bad_config_rejected(tmp_path):
    inj = FaultInjector()
    p = tmp_path / "bad.json"
    p.write_text(json.dumps({"sites": {"x": {"injectionType": "nope"}}}))
    with pytest.raises(ValueError, match="injectionType"):
        inj.load_config(str(p))


# ---- JAX-boundary shim + retry/quarantine contract (faultinj.cu:125-131,
# faultinj/README.md:3-16) --------------------------------------------------

from spark_rapids_jni_tpu.faultinj import jax_shim
from spark_rapids_jni_tpu.faultinj.resilience import (DeviceQuarantined,
                                                      ResilientExecutor)


@pytest.fixture
def shim():
    sites = jax_shim.install()
    yield sites
    jax_shim.uninstall()


def _device_work():
    import jax.numpy as jnp
    # fresh data each call so the computation actually dispatches
    x = jnp.asarray(np.random.default_rng(0).integers(0, 9, 64))
    return int(jnp.sum(x))


def test_shim_intercepts_execute(tmp_path, shim):
    assert "jax.execute" in shim
    faultinj.enable(write_cfg(tmp_path, {
        "seed": 1,
        "sites": {"jax.execute": {"percent": 100,
                                  "interceptionCount": 1,
                                  "injectionType": "device_error"}}}))
    with pytest.raises(InjectedDeviceError):
        _device_work()
    # budget spent — next call reaches the device
    assert _device_work() == int(np.sum(
        np.random.default_rng(0).integers(0, 9, 64)))


def test_executor_retries_transient_then_succeeds(tmp_path, shim):
    faultinj.enable(write_cfg(tmp_path, {
        "seed": 1,
        "sites": {"jax.execute": {"percent": 100,
                                  "interceptionCount": 2,
                                  "injectionType": "oom"}}}))
    ex = ResilientExecutor(max_retries=3)
    assert ex.submit(_device_work) == _device_work()
    assert ex.retry_count == 2
    assert not ex.quarantined


def test_executor_quarantines_on_fatal(tmp_path, shim):
    faultinj.enable(write_cfg(tmp_path, {
        "seed": 1,
        "sites": {"jax.execute": {"percent": 100,
                                  "interceptionCount": 1,
                                  "injectionType": "device_error"}}}))
    ex = ResilientExecutor(max_retries=3)
    with pytest.raises(DeviceQuarantined):
        ex.submit(_device_work)
    assert ex.quarantined
    # quarantined executor fails fast without touching the device
    with pytest.raises(DeviceQuarantined):
        ex.submit(_device_work)
    assert ex.fatal_count == 1


def test_shim_device_conversion_retry_end_to_end(tmp_path, shim):
    """A real device call (JCUDF conversion) failed by the shim is retried
    by the executor and completes — the reference's tier-3 contract."""
    faultinj.enable(write_cfg(tmp_path, {
        "seed": 1,
        "sites": {"jax.execute": {"percent": 100,
                                  "interceptionCount": 1,
                                  "injectionType": "oom"}}}))
    ex = ResilientExecutor(max_retries=2)

    def work():
        batches = convert_to_rows(small_table())
        return int(np.asarray(batches[0].data).sum())

    assert ex.submit(work) == work()
    assert ex.retry_count >= 1


def test_shim_uninstall_restores(shim):
    jax_shim.uninstall()
    assert not jax_shim.installed()
    # no interception after uninstall even with an aggressive config
    inj = faultinj.get_injector()
    inj._rules = {}
    assert _device_work() >= 0


def test_shim_sees_repeat_cached_executions(tmp_path, shim):
    # CUPTI parity (faultinj.cu:125-131): the steady state of a long-running
    # executor is REPEAT executions of an already-compiled signature.  With
    # the C++ fastpath active those bypass Python entirely; the shim
    # disables it, so a fault armed AFTER several warm executions must still
    # fire on the next (cached) call.
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(x):
        return jnp.sum(x * 2)

    x = jnp.arange(128)
    for _ in range(3):                 # compile + warm repeats, no faults
        assert int(step(x)) == int(np.arange(128).sum() * 2)
    faultinj.enable(write_cfg(tmp_path, {
        "seed": 1,
        "sites": {"jax.execute": {"percent": 100,
                                  "interceptionCount": 1,
                                  "injectionType": "device_error"}}}))
    with pytest.raises(InjectedDeviceError):
        step(x)                        # cached signature — must still trap
    assert int(step(x)) == int(np.arange(128).sum() * 2)  # budget spent


def test_executor_recovers_mid_query_on_cached_execution(tmp_path, shim):
    # kill a CACHED execution mid-"query" and recover via the retry policy
    import jax
    import jax.numpy as jnp

    @jax.jit
    def stage(x):
        return jnp.cumsum(x)

    x = jnp.arange(64)
    warm = np.asarray(stage(x))        # compiled + executed once
    faultinj.enable(write_cfg(tmp_path, {
        "seed": 1,
        "sites": {"jax.execute": {"percent": 100,
                                  "interceptionCount": 1,
                                  "injectionType": "oom"}}}))
    ex = ResilientExecutor(max_retries=2)
    out = ex.submit(lambda: np.asarray(stage(x)))
    np.testing.assert_array_equal(out, warm)


# ---- chaos-harness extensions: device targeting, hit caps, watcher
# robustness, recovery state machine ----------------------------------------

from spark_rapids_jni_tpu.faultinj import injector as finj_mod


def test_device_targeted_rule(tmp_path):
    inj = faultinj.get_injector()
    inj.load_dict({"sites": {"convert_to_rows": {
        "percent": 100, "injectionType": "device_error",
        "device": "cpu:3"}}})
    inj.enable()
    # outside any device scope: the rule is pinned elsewhere, no fire
    assert len(convert_to_rows(small_table())) == 1
    # wrong device scope: no fire either
    with finj_mod.device_scope("cpu:1"):
        assert len(convert_to_rows(small_table())) == 1
    # the targeted device faults
    with finj_mod.device_scope("cpu:3"):
        with pytest.raises(InjectedDeviceError):
            convert_to_rows(small_table())


def test_device_mismatch_does_not_fall_through_to_wildcard(tmp_path):
    # a named rule that exists but targets another device means "this
    # site is configured, just not here" — the wildcard must not revive it
    inj = faultinj.get_injector()
    inj.load_dict({"sites": {
        "convert_to_rows": {"percent": 100,
                            "injectionType": "device_error",
                            "device": "cpu:7"},
        "*": {"percent": 100, "injectionType": "oom"}}})
    inj.enable()
    with finj_mod.device_scope("cpu:1"):
        assert len(convert_to_rows(small_table())) == 1
    # an UNNAMED site still falls to the wildcard on any device
    with finj_mod.device_scope("cpu:1"):
        with pytest.raises(InjectedOomError):
            inj.check("some.other.site")


def test_device_scope_nests_and_restores():
    assert finj_mod.current_device() is None
    with finj_mod.device_scope("cpu:0"):
        assert finj_mod.current_device() == "cpu:0"
        with finj_mod.device_scope("cpu:5"):
            assert finj_mod.current_device() == "cpu:5"
        assert finj_mod.current_device() == "cpu:0"
    assert finj_mod.current_device() is None


def test_max_hits_one_shot(tmp_path):
    # maxHits caps FIRES (not interceptions): the one-shot kill used by
    # the chaos harness — exactly one fault, then genuinely healthy
    inj = faultinj.get_injector()
    inj.load_dict({"sites": {"convert_to_rows": {
        "percent": 100, "injectionType": "device_error", "maxHits": 2}}})
    inj.enable()
    for _ in range(2):
        with pytest.raises(InjectedDeviceError):
            convert_to_rows(small_table())
    for _ in range(3):
        assert len(convert_to_rows(small_table())) == 1
    assert inj.injected_count == 2


def test_watcher_survives_bad_edit(tmp_path):
    # regression: a torn/bad config edit must not kill the watcher — the
    # old schedule stays armed and a later good edit still reloads
    path = write_cfg(tmp_path, {"dynamic": True, "sites": {}})
    faultinj.enable(path)
    inj = faultinj.get_injector()
    assert len(convert_to_rows(small_table())) == 1
    time.sleep(0.05)
    with open(path, "w") as f:
        f.write("{ this is not json")
    os.utime(path)
    time.sleep(0.6)                       # ≥2 poll intervals
    assert inj._watcher is not None and inj._watcher.is_alive()
    assert len(convert_to_rows(small_table())) == 1   # old (empty) rules
    with open(path, "w") as f:
        json.dump({"dynamic": True,
                   "sites": {"convert_to_rows": {"percent": 100}}}, f)
    os.utime(path)
    deadline = time.time() + 5
    fired = False
    while time.time() < deadline:
        try:
            convert_to_rows(small_table())
        except InjectedDeviceError:
            fired = True
            break
        time.sleep(0.1)
    assert fired, "watcher dead after bad edit — good edit never loaded"


def test_watcher_stops_on_dynamic_false(tmp_path):
    # config edited to dynamic:false → that edit loads, then the
    # schedule freezes: later edits are ignored
    path = write_cfg(tmp_path, {"dynamic": True, "sites": {}})
    faultinj.enable(path)
    inj = faultinj.get_injector()
    time.sleep(0.05)
    with open(path, "w") as f:
        json.dump({"dynamic": False,
                   "sites": {"convert_to_rows": {"percent": 100}}}, f)
    os.utime(path)
    deadline = time.time() + 5
    while time.time() < deadline:
        try:
            convert_to_rows(small_table())
            time.sleep(0.1)
        except InjectedDeviceError:
            break
    else:
        pytest.fail("dynamic:false edit never loaded")
    assert inj._watcher is None           # watcher shut down
    time.sleep(0.05)
    with open(path, "w") as f:
        json.dump({"dynamic": True, "sites": {}}, f)    # would disarm
    os.utime(path)
    time.sleep(0.6)
    with pytest.raises(InjectedDeviceError):
        convert_to_rows(small_table())    # frozen schedule still armed


def test_backoff_delay_bounds():
    ex = ResilientExecutor(backoff_s=0.1, backoff_max_s=0.5, jitter=0.5,
                           seed=1)
    for _ in range(20):
        assert 0.1 <= ex.backoff_delay(1) <= 0.15 + 1e-9
        # 0.1 * 2^3 = 0.8 capped at 0.5; jitter ≤ +50%
        assert 0.5 <= ex.backoff_delay(4) <= 0.75 + 1e-9
    assert ResilientExecutor().backoff_delay(3) == 0.0   # backoff off


def test_transient_retry_uses_backoff(tmp_path, shim):
    faultinj.enable(write_cfg(tmp_path, {
        "seed": 1,
        "sites": {"jax.execute": {"percent": 100,
                                  "interceptionCount": 2,
                                  "injectionType": "oom"}}}))
    ex = ResilientExecutor(max_retries=3, backoff_s=0.01,
                           backoff_max_s=0.05, seed=2)
    t0 = time.monotonic()
    assert ex.submit(_device_work) == _device_work()
    assert ex.retry_count == 2
    assert time.monotonic() - t0 >= 0.02   # two backoff sleeps happened


def test_recover_state_machine(tmp_path, shim):
    faultinj.enable(write_cfg(tmp_path, {
        "seed": 1,
        "sites": {"jax.execute": {"percent": 100,
                                  "interceptionCount": 1,
                                  "injectionType": "device_error"}}}))
    ex = ResilientExecutor(max_retries=1, device="cpu:2")
    assert ex.recover() is False           # healthy: recover is a no-op
    with pytest.raises(DeviceQuarantined):
        ex.submit(_device_work)
    assert ex.state == "quarantined"
    with pytest.raises(DeviceQuarantined):
        ex.submit(_device_work)            # fail-fast while quarantined
    assert ex.recover() is True
    assert ex.state == "probation"
    assert ex.recover() is False           # already probing
    # canary success (fault budget spent) re-admits
    assert ex.submit(_device_work) == _device_work()
    assert ex.state == "healthy"
    assert ex.recovery_count == 1


def test_probation_requarantines_on_fatal_canary(tmp_path, shim):
    faultinj.enable(write_cfg(tmp_path, {
        "seed": 1,
        "sites": {"jax.execute": {"percent": 100,
                                  "interceptionCount": 2,
                                  "injectionType": "device_error"}}}))
    ex = ResilientExecutor(max_retries=1)
    with pytest.raises(DeviceQuarantined):
        ex.submit(_device_work)
    assert ex.recover() is True
    with pytest.raises(DeviceQuarantined):
        ex.submit(_device_work)            # canary hits the second fault
    assert ex.state == "quarantined"
    assert ex.fatal_count == 2


def test_fail_probation_falls_back_to_quarantined(tmp_path, shim):
    faultinj.enable(write_cfg(tmp_path, {
        "seed": 1,
        "sites": {"jax.execute": {"percent": 100,
                                  "interceptionCount": 1,
                                  "injectionType": "device_error"}}}))
    ex = ResilientExecutor(max_retries=1)
    with pytest.raises(DeviceQuarantined):
        ex.submit(_device_work)
    assert ex.recover() is True
    ex.fail_probation()                    # canary errored non-fatally
    assert ex.state == "quarantined"
    with pytest.raises(DeviceQuarantined):
        ex.submit(_device_work)
