"""Dictionary-string fast path differentials.

The scan keeps fully dictionary-encoded string columns as
:class:`DictColumn` (int32 codes + dictionary) and every relational op
consumes the codes; bytes materialize only at the output boundary
(rowconv / host extraction).  These tests hold the contract three ways:

* **differential** — every op (filter, join, groupby, sort, rowconv) on
  the dict path is bit-identical to the forced-materialized path
  (``SRJT_DICT_STRINGS=0``) and agrees with a pandas oracle;
* **laziness** — the dict path never bumps ``strings.dict.materialize``
  before the output boundary (counter-asserted);
* **runtime parity** — results survive capture/replay compilation and
  the concurrent exec scheduler unchanged.
"""

import io

import numpy as np
import pandas as pd
import pytest

import jax.numpy as jnp

from spark_rapids_jni_tpu import types as T
from spark_rapids_jni_tpu.column import Column, DictColumn, Table, as_dict_column
from spark_rapids_jni_tpu.ops import filter as F
from spark_rapids_jni_tpu.ops import groupby as G
from spark_rapids_jni_tpu.ops import join_plan as J
from spark_rapids_jni_tpu.ops import sort as SORT
from spark_rapids_jni_tpu.ops import strings as S
from spark_rapids_jni_tpu.parquet import decode, device_scan
from spark_rapids_jni_tpu.rowconv import convert as RC
from spark_rapids_jni_tpu.utils import metrics


def _write(cols: dict, row_group_size=2_000, use_dictionary=True) -> bytes:
    import pyarrow as pa
    import pyarrow.parquet as pq
    t = pa.table(cols)
    buf = io.BytesIO()
    pq.write_table(t, buf, use_dictionary=use_dictionary,
                   row_group_size=row_group_size)
    return buf.getvalue()


def _strings(n, card, null_p, seed, prefix="brand"):
    rng = np.random.default_rng(seed)
    return [None if rng.random() < null_p
            else f"{prefix}_{rng.integers(0, card):03d}" for _ in range(n)]


@pytest.fixture(scope="module")
def raw():
    import pyarrow as pa
    n = 6_000
    rng = np.random.default_rng(5)
    return _write({
        "s": pa.array(_strings(n, 24, 0.12, 5), pa.string()),
        "k": rng.integers(0, 40, n).astype(np.int64),
        "x": rng.integers(-100, 100, n).astype(np.int64),
    })


def _scan_dict(raw_bytes) -> Table:
    t = device_scan.scan_table(raw_bytes)
    assert isinstance(t[0], DictColumn), "scan did not keep dict codes"
    return t


def _scan_mat(raw_bytes, monkeypatch) -> Table:
    monkeypatch.setenv("SRJT_DICT_STRINGS", "0")
    try:
        t = device_scan.scan_table(raw_bytes)
    finally:
        monkeypatch.delenv("SRJT_DICT_STRINGS", raising=False)
    assert as_dict_column(t[0]) is None
    return t


def _df(raw_bytes) -> pd.DataFrame:
    import pyarrow.parquet as pq
    return pq.read_table(io.BytesIO(raw_bytes)).to_pandas()


def _mask_arr(m: Column) -> jnp.ndarray:
    bits = m.data != 0
    return bits if m.validity is None else bits & m.validity


def _cols_equal(a: Column, b: Column):
    assert a.to_pylist() == b.to_pylist()


def _tables_equal(a: Table, b: Table):
    assert a.num_columns == b.num_columns
    for ca, cb in zip(a.columns, b.columns):
        _cols_equal(ca, cb)


# --- scan + laziness --------------------------------------------------------


def test_scan_matches_host_decode(raw):
    t = _scan_dict(raw)
    ref = decode.read_table(raw)
    _tables_equal(t, ref)


def test_dict_path_is_lazy_until_output(raw):
    metrics.set_enabled(True)
    try:
        base = metrics.snapshot()["counters"]

        def delta(name):
            snap = metrics.snapshot()["counters"]
            return snap.get(name, 0) - base.get(name, 0)

        t = _scan_dict(raw)
        assert delta("plan.scan.dict_cols") >= 1
        assert delta("parquet.pages.dict") >= 1
        col = t[0]
        mask = S.like(col, "%_00%")
        ft = F.mask_table(t, _mask_arr(mask))
        gt = G.groupby_aggregate(ft, [0], [(2, "sum")])
        perm = SORT.order_by(t, [0, 1], [True, True])
        F.gather(t, perm)
        assert delta("strings.dict.predicate") >= 1
        assert delta("strings.dict.gather") >= 1
        # nothing above may touch string bytes
        assert delta("strings.dict.materialize") == 0
        del gt
        # ...until the output boundary does
        _ = col.data
        assert delta("strings.dict.materialize") == 1
    finally:
        metrics.set_enabled(None)


def test_knob_forces_materialized_scan(raw, monkeypatch):
    t = _scan_mat(raw, monkeypatch)
    _tables_equal(t, decode.read_table(raw))


# --- filter -----------------------------------------------------------------


@pytest.mark.parametrize("pred", ["eq", "starts", "like", "isin"])
def test_filter_differential(raw, monkeypatch, pred):
    td, tm, df = _scan_dict(raw), _scan_mat(raw, monkeypatch), _df(raw)
    sd, sm = td[0], tm[0]
    if pred == "eq":
        md, mm = S.equal_to_scalar(sd, "brand_003"), S.equal_to_scalar(sm, "brand_003")
        want = df["s"] == "brand_003"
    elif pred == "starts":
        md, mm = S.starts_with(sd, "brand_01"), S.starts_with(sm, "brand_01")
        want = df["s"].str.startswith("brand_01")
    elif pred == "like":
        md, mm = S.like(sd, "%d_02%"), S.like(sm, "%d_02%")
        want = df["s"].str.contains("d_02", regex=False)
    else:
        vals = ["brand_001", "brand_017", "missing"]
        md = Column(T.bool8, F.isin(sd, vals))
        mm = Column(T.bool8, F.isin(sm, vals))
        want = df["s"].isin(vals)
    want = (want == True).to_numpy(dtype=bool)   # noqa: E712 (NaN → False)

    bd, bm = np.asarray(_mask_arr(md)), np.asarray(_mask_arr(mm))
    np.testing.assert_array_equal(bd, want)
    np.testing.assert_array_equal(bd, bm)
    fd = F.apply_boolean_mask(td, jnp.asarray(bd))
    fm = F.apply_boolean_mask(tm, jnp.asarray(bm))
    assert isinstance(fd[0], DictColumn)   # filtered rows stay codes
    _tables_equal(fd, fm)
    assert fd[0].to_pylist() == df["s"][want].tolist()
    md2 = F.mask_table(td, jnp.asarray(bd))   # non-compacting variant
    mm2 = F.mask_table(tm, jnp.asarray(bm))
    assert isinstance(md2[0], DictColumn)
    _tables_equal(md2, mm2)


# --- sort -------------------------------------------------------------------


def test_sort_permutation_bit_identical(raw, monkeypatch):
    td, tm = _scan_dict(raw), _scan_mat(raw, monkeypatch)
    for asc in (True, False):
        pd_ = np.asarray(SORT.order_by(td, [0, 2], [asc, True]))
        pm = np.asarray(SORT.order_by(tm, [0, 2], [asc, True]))
        np.testing.assert_array_equal(pd_, pm)
    perm = SORT.order_by(td, [0], [True])
    got = F.gather(td, perm)[0].to_pylist()
    nn = sorted(v for v in _df(raw)["s"].tolist() if v is not None)
    assert [v for v in got if v is not None] == nn


# --- groupby ----------------------------------------------------------------


def test_groupby_differential(raw, monkeypatch):
    td, tm, df = _scan_dict(raw), _scan_mat(raw, monkeypatch), _df(raw)
    gd = G.groupby_aggregate(td, [0], [(2, "sum")])
    gm = G.groupby_aggregate(tm, [0], [(2, "sum")])
    _tables_equal(gd, gm)
    want = df[df["s"].notna()].groupby("s")["x"].sum().to_dict()
    got = dict(zip(gd[0].to_pylist(), gd[1].to_pylist()))
    for k, v in want.items():
        assert got[k] == v


# --- join (multi-file, incompatible per-file dictionaries) ------------------


@pytest.mark.slow      # heaviest dict-path JIT in the module (~37 s)
def test_join_across_incompatible_dictionaries(raw, monkeypatch):
    import pyarrow as pa
    # second file: overlapping-but-different dictionary (other card/order)
    n2 = 3_000
    rng = np.random.default_rng(9)
    raw2 = _write({
        "s": pa.array(_strings(n2, 30, 0.1, 9), pa.string()),
        "y": rng.integers(0, 10, n2).astype(np.int64),
    }, row_group_size=1_100)
    ld, rd = _scan_dict(raw), _scan_dict(raw2)
    lm, rm = _scan_mat(raw, monkeypatch), _scan_mat(raw2, monkeypatch)
    # per-file dictionaries differ: shared encode must reconcile them
    jd = J.join_aggregate(ld, rd, [0], [0], group_keys=[0], aggs=[(2, "sum")])
    jm = J.join_aggregate(lm, rm, [0], [0], group_keys=[0], aggs=[(2, "sum")])
    _tables_equal(jd, jm)
    dfl, dfr = _df(raw), _df(raw2)
    merged = dfl.merge(dfr, on="s")
    want = merged.groupby("s")["x"].sum().to_dict()
    got = dict(zip(jd[0].to_pylist(), jd[1].to_pylist()))
    assert {k: v for k, v in got.items() if k is not None} == want


def test_encode_shared_consistency(raw, monkeypatch):
    import pyarrow as pa
    raw2 = _write({"s": pa.array(_strings(2_000, 8, 0.2, 3), pa.string())})
    a, b = _scan_dict(raw)[0], _scan_dict(raw2)[0]
    ea, eb = S.encode_shared([a, b])
    strs = a.to_pylist() + b.to_pylist()
    codes = np.asarray(ea.data).tolist() + np.asarray(eb.data).tolist()
    seen = {}
    for c, v in zip(codes, strs):
        if v is None:
            continue
        assert seen.setdefault(c, v) == v        # one code ↔ one string
    assert len(set(seen.values())) == len(seen)  # one string ↔ one code


# --- rowconv ----------------------------------------------------------------


def test_rowconv_boundary_bit_identical(raw, monkeypatch):
    td, tm = _scan_dict(raw), _scan_mat(raw, monkeypatch)
    bd, bm = RC.convert_to_rows(td), RC.convert_to_rows(tm)
    assert len(bd) == len(bm)
    for x, y in zip(bd, bm):
        np.testing.assert_array_equal(np.asarray(x.data), np.asarray(y.data))


def test_rowconv_dict_passthrough(raw):
    td = _scan_dict(raw)
    enc, dicts = RC.dict_encode_for_rows(td)
    assert list(dicts) == [0]
    assert enc[0].dtype.id == T.int32.id      # codes ride the fixed path
    batches = RC.convert_to_rows(enc)
    parts = [RC.convert_from_rows(b, [c.dtype for c in enc.columns])
             for b in batches]
    assert len(parts) == 1
    back = RC.restore_dict_columns(parts[0], dicts)
    assert isinstance(back[0], DictColumn)
    _tables_equal(back, decode.read_table(raw))


# --- edges: null codes, empty dictionary ------------------------------------


def test_heavy_nulls(monkeypatch):
    import pyarrow as pa
    rawn = _write({"s": pa.array(_strings(3_000, 5, 0.85, 7), pa.string()),
                   "x": np.arange(3_000, dtype=np.int64)})
    tn = device_scan.scan_table(rawn)
    _tables_equal(tn, decode.read_table(rawn))
    d = as_dict_column(tn[0])
    if d is not None:
        m = S.equal_to_scalar(tn[0], "brand_002")
        bits = (np.asarray(m.data) != 0) & np.asarray(m.validity)
        want = np.array([v == "brand_002" if v is not None else False
                         for v in _df(rawn)["s"]])
        np.testing.assert_array_equal(bits, want)


def test_all_null_column():
    import pyarrow as pa
    rawn = _write({"s": pa.array([None] * 500, pa.string()),
                   "x": np.arange(500, dtype=np.int64)})
    tn = device_scan.scan_table(rawn)
    _tables_equal(tn, decode.read_table(rawn))


def test_empty_dictionary_unit():
    # a DictColumn over a zero-entry dictionary (every row null)
    empty = Column(T.string, jnp.zeros(0, jnp.uint8), jnp.zeros(1, jnp.int32))
    d = DictColumn(jnp.zeros(7, jnp.int32), empty,
                   jnp.zeros(7, bool))
    assert d.to_pylist() == [None] * 7
    m = S.equal_to_scalar(d, "anything")
    assert not (np.asarray(m.data) != 0).any()
    mat = d.materialize()
    assert np.asarray(mat.offsets).tolist() == [0] * 8


# --- runtime parity: capture/replay + concurrent scheduler ------------------


def _qfn(tables):
    t = tables["t"]
    m = S.starts_with(t[0], "brand_0")
    ft = F.mask_table(t, _mask_arr(m))
    g = G.groupby_aggregate(ft, [0], [(2, "sum")])
    perm = SORT.order_by(g, [0], [True])
    return F.gather(g, perm)


def test_capture_replay_bit_identity(raw):
    from spark_rapids_jni_tpu.models.compiled import compile_query
    tables = {"t": _scan_dict(raw)}
    cq = compile_query(_qfn, tables)
    out = cq.run(tables)
    _tables_equal(out, cq.expected)
    out2 = cq.run_unchecked(tables)
    _tables_equal(out2, cq.expected)


def test_scheduler_bit_identity(raw):
    from spark_rapids_jni_tpu import exec as xc
    tables = {"t": _scan_dict(raw)}
    want = _qfn(tables)
    with xc.QueryScheduler(workers=2) as sched:
        tickets = [sched.submit(f"dictq{i}", _qfn, tables) for i in range(4)]
        for tk in tickets:
            _tables_equal(tk.result(timeout=300), want)
