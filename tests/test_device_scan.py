"""Device-side Parquet scan vs the host decoder — byte-exact differential
across encodings, codecs, nulls, dictionaries, and fallback columns."""

import io

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_jni_tpu.parquet import decode, device_scan

RNG = np.random.default_rng(17)


def write(t: pa.Table, **kw) -> bytes:
    buf = io.BytesIO()
    pq.write_table(t, buf, **kw)
    return buf.getvalue()


def assert_tables_match(dev, host):
    assert dev.num_columns == host.num_columns
    for a, b in zip(dev.columns, host.columns):
        assert a.dtype == b.dtype
        np.testing.assert_array_equal(np.asarray(a.data), np.asarray(b.data))
        va = np.asarray(a.validity_or_true())
        vb = np.asarray(b.validity_or_true())
        np.testing.assert_array_equal(va, vb)


@pytest.mark.parametrize("compression", ["NONE", "SNAPPY"])
@pytest.mark.parametrize("use_dictionary", [False, True])
def test_fixed_width_matrix(compression, use_dictionary):
    n = 20_000
    t = pa.table({
        "i32": pa.array(RNG.integers(-10**9, 10**9, n, dtype=np.int32)),
        "i64": pa.array(RNG.integers(-10**18, 10**18, n, dtype=np.int64)),
        "f32": pa.array(RNG.standard_normal(n).astype(np.float32)),
        "f64": pa.array(RNG.standard_normal(n)),
        # low cardinality: dictionary encoding stays dictionary-encoded
        "lowcard": pa.array(RNG.integers(0, 50, n, dtype=np.int64)),
    })
    raw = write(t, compression=compression, use_dictionary=use_dictionary,
                row_group_size=6000)
    assert_tables_match(device_scan.scan_table(raw), decode.read_table(raw))


def test_nulls_def_level_expansion():
    n = 9000
    vals = RNG.standard_normal(n)
    mask = RNG.random(n) < 0.8
    arr = pa.array(pd.array(np.where(mask, vals, np.nan),
                            dtype="float64").to_numpy(),
                   mask=~mask)
    i64 = pa.array(RNG.integers(0, 10**6, n, dtype=np.int64),
                   mask=RNG.random(n) < 0.1)
    t = pa.table({"f64n": arr, "i64n": i64})
    raw = write(t, compression="SNAPPY", use_dictionary=False,
                row_group_size=2500)
    assert_tables_match(device_scan.scan_table(raw), decode.read_table(raw))


@pytest.mark.slow
def test_mixed_fallback_columns():
    # strings + date32 + f64: strings fall back to the host decoder, the
    # rest ride the device path — column order must be preserved
    n = 5000
    t = pa.table({
        "s": pa.array([f"row{i % 97}" for i in range(n)]),
        "d": pa.array(RNG.integers(8000, 12000, n, dtype=np.int32),
                      pa.date32()),
        "v": pa.array(RNG.standard_normal(n)),
    })
    raw = write(t, compression="SNAPPY")
    assert_tables_match(device_scan.scan_table(raw), decode.read_table(raw))


def test_column_selection_order():
    n = 1000
    t = pa.table({
        "a": pa.array(np.arange(n, dtype=np.int64)),
        "b": pa.array(np.arange(n, dtype=np.int32) * 2),
        "c": pa.array(RNG.standard_normal(n)),
    })
    raw = write(t, use_dictionary=False)
    dev = device_scan.scan_table(raw, columns=["c", "a"])
    host = decode.read_table(raw, columns=["c", "a"])
    assert_tables_match(dev, host)


def test_q6_pipeline_via_device_scan():
    from tests.test_parquet_decode import make_lineitem
    from spark_rapids_jni_tpu.models import q6
    raw, df = make_lineitem(12_000)
    lo, hi = 8766, 8766 + 365
    table = device_scan.scan_table(raw, columns=q6.COLUMNS)
    qv, ep, disc, ship = (table[i].values() for i in range(4))
    import jax.numpy as jnp
    revenue, matched = q6.q6_kernel(qv, ep, disc, ship,
                                    jnp.int32(lo), jnp.int32(hi))
    m = ((df.l_shipdate >= lo) & (df.l_shipdate < hi)
         & (df.l_discount >= 0.05) & (df.l_discount <= 0.07)
         & (df.l_quantity < 24))
    expect = float((df.l_extendedprice[m] * df.l_discount[m]).sum())
    assert int(matched) == int(m.sum())
    np.testing.assert_allclose(float(revenue), expect, rtol=1e-9)


def test_all_null_column():
    # a fully-null optional column has ZERO present values — the def-level
    # expansion must produce an all-null column, not crash on an empty
    # gather (round-3 review finding)
    n = 100
    t = pa.table({
        "allnull": pa.array([None] * n, pa.float64()),
        "ok": pa.array(np.arange(n, dtype=np.int64)),
    })
    for use_dict in (False, True):
        raw = write(t, use_dictionary=use_dict)
        assert_tables_match(device_scan.scan_table(raw),
                            decode.read_table(raw))


def test_flba_decimals_device():
    # FLBA DECIMAL across precisions (decimal32/64/128 narrowing), codecs,
    # dictionary encodings, and nulls — device limbs vs host oracle
    import decimal as pydec
    n = 4000
    rng = np.random.default_rng(23)
    cents = rng.integers(-10**6, 10**6, n)
    big = [int(x) * 10**20 for x in rng.integers(-10**9, 10**9, n)]
    mask = rng.random(n) < 0.15
    t = pa.table({
        "d32": pa.array([pydec.Decimal(int(c)) / 100 for c in cents],
                        pa.decimal128(7, 2)),
        "d64": pa.array([pydec.Decimal(int(c) * 10**6) / 10**4
                         for c in cents], pa.decimal128(16, 4)),
        "d128": pa.array([pydec.Decimal(v) / 10**6 for v in big],
                         pa.decimal128(38, 6)),
        "d32n": pa.array([None if m else pydec.Decimal(int(c)) / 100
                          for m, c in zip(mask, cents)],
                         pa.decimal128(7, 2)),
    })
    for compression in ("NONE", "SNAPPY"):
        for use_dict in (False, True):
            raw = write(t, compression=compression,
                        use_dictionary=use_dict, row_group_size=1500)
            assert_tables_match(device_scan.scan_table(raw),
                                decode.read_table(raw))


def test_int_phys_decimals_device():
    # DECIMAL carried on INT32/INT64 physical types (Spark writers)
    n = 2000
    rng = np.random.default_rng(29)
    import decimal as pydec
    t = pa.table({
        "p32": pa.array([pydec.Decimal(int(v)) / 100
                         for v in rng.integers(-10**7, 10**7, n)],
                        pa.decimal128(9, 2)),
        "p64": pa.array([pydec.Decimal(int(v)) / 10**4
                         for v in rng.integers(-10**13, 10**13, n)],
                        pa.decimal128(18, 4)),
    })
    import pyarrow.parquet as _pq
    import io as _io
    buf = _io.BytesIO()
    _pq.write_table(t, buf, use_dictionary=False,
                    store_decimal_as_integer=True)
    raw = buf.getvalue()
    assert_tables_match(device_scan.scan_table(raw),
                        decode.read_table(raw))


def test_non_decimal_flba_falls_back():
    # fixed_size_binary without a DECIMAL annotation (UUIDs/hashes) must
    # ride the host decoder, not the decimal limb path
    n = 300
    vals = [bytes([i % 251] * 8) for i in range(n)]
    t = pa.table({"u": pa.array(vals, pa.binary(8)),
                  "x": pa.array(np.arange(n, dtype=np.int64))})
    raw = write(t, use_dictionary=False)
    assert_tables_match(device_scan.scan_table(raw), decode.read_table(raw))


@pytest.mark.parametrize("compression", ["NONE", "SNAPPY"])
@pytest.mark.slow
def test_plain_strings_on_device(compression):
    """VERDICT r3 #2 done-criterion: a string column decoded ON DEVICE —
    scan_column_device must handle the PLAIN string chunk itself (no host
    fallback) and match the host decoder byte-exactly."""
    words = ["", "tpu", "spark-rapids", "columnar row transcode",
             "x" * 40, "payload"]
    n = 4000
    strs = [words[i % len(words)] if i % 11 else None for i in range(n)]
    t = pa.table({
        "s": pa.array(strs),
        "v": pa.array(RNG.integers(0, 1 << 30, n).astype(np.int64)),
    })
    raw = write(t, compression=compression, use_dictionary=False)
    dev = device_scan.scan_table(raw)
    host = decode.read_table(raw)
    assert_tables_match(dev, host)
    offs_d = np.asarray(dev.columns[0].offsets)
    offs_h = np.asarray(host.columns[0].offsets)
    np.testing.assert_array_equal(offs_d, offs_h)


def test_plain_booleans_on_device():
    n = 3000
    vals = RNG.integers(0, 2, n).astype(bool)
    mask = RNG.random(n) < 0.1
    t = pa.table({"b": pa.array(vals, mask=mask),
                  "k": pa.array(np.arange(n, dtype=np.int32))})
    raw = write(t, use_dictionary=False)
    assert_tables_match(device_scan.scan_table(raw),
                        decode.read_table(raw))


@pytest.mark.slow
def test_device_scan_strings_not_fallback(monkeypatch):
    """Prove the string column goes through the DEVICE path: poison the
    host per-column decoder and scan anyway."""
    n = 2048
    t = pa.table({"s": pa.array([f"name-{i % 97}" for i in range(n)])})
    raw = write(t, use_dictionary=False)

    def boom(*a, **k):
        raise AssertionError("host column decode reached")
    monkeypatch.setattr(device_scan.D, "read_table", boom)
    dev = device_scan.scan_table(raw)
    assert dev.columns[0].to_pylist()[:3] == ["name-0", "name-1", "name-2"]


# ---- dictionary strings + device RLE (round 5) -----------------------------

def _str_cols_equal(a, b):
    np.testing.assert_array_equal(np.asarray(a.data), np.asarray(b.data))
    np.testing.assert_array_equal(np.asarray(a.offsets),
                                  np.asarray(b.offsets))
    np.testing.assert_array_equal(np.asarray(a.validity_or_true()),
                                  np.asarray(b.validity_or_true()))


@pytest.mark.parametrize("compression", ["NONE", "SNAPPY"])
@pytest.mark.parametrize("with_nulls", [False, True])
@pytest.mark.slow
def test_dict_strings_on_device(compression, with_nulls):
    """Dictionary-encoded strings — the dominant real-world string
    encoding — must decode byte-exactly through the device path."""
    n = 5000
    words = ["", "tpu", "spark-rapids", "a-much-longer-dictionary-entry",
             "x" * 95, "payload", "ünïcodé-bytes"]
    vals = [words[i] for i in RNG.integers(0, len(words), n)]
    if with_nulls:
        vals = [None if RNG.random() < 0.15 else v for v in vals]
    t = pa.table({"s": pa.array(vals, pa.string()),
                  "k": pa.array(RNG.integers(0, 50, n), pa.int64())})
    raw = write(t, compression=compression, use_dictionary=True,
                row_group_size=1800)        # multiple chunks
    dev = device_scan.scan_table(raw)
    host = decode.read_table(raw)
    _str_cols_equal(dev.columns[0], host.columns[0])
    np.testing.assert_array_equal(np.asarray(dev.columns[1].data),
                                  np.asarray(host.columns[1].data))


@pytest.mark.slow
def test_dict_strings_not_fallback(monkeypatch):
    """Prove dictionary strings decode on the DEVICE path (no host column
    decoder involvement)."""
    n = 3000
    t = pa.table({"s": pa.array([f"name-{i % 37}" for i in range(n)])})
    raw = write(t, use_dictionary=True)

    def boom(*a, **k):
        raise AssertionError("host column decode reached")
    monkeypatch.setattr(device_scan.D, "read_table", boom)
    dev = device_scan.scan_table(raw)
    assert dev.columns[0].to_pylist()[:3] == ["name-0", "name-1", "name-2"]


def test_dict_indices_expand_on_device(monkeypatch):
    """The dictionary-index RLE stream must expand on device: poison the
    host hybrid decoder and scan a dict-encoded fixed-width column."""
    n = 4096
    t = pa.table({"v": pa.array(RNG.integers(0, 200, n), pa.int32())})
    raw = write(t, use_dictionary=True)
    host = decode.read_table(raw)          # oracle BEFORE the poison

    def boom(*a, **k):
        raise AssertionError("host RLE decode reached")
    monkeypatch.setattr(device_scan.D, "decode_rle_bitpacked_hybrid", boom)
    dev = device_scan.scan_table(raw)
    np.testing.assert_array_equal(np.asarray(dev.columns[0].data),
                                  np.asarray(host.columns[0].data))


def test_def_levels_expand_on_device(monkeypatch):
    """Nullable fixed-width columns: the def-level stream expands on
    device too (run headers walked on host, payload bit-tested on chip)."""
    n = 3000
    vals = [None if RNG.random() < 0.2 else int(v)
            for v in RNG.integers(0, 1000, n)]
    t = pa.table({"v": pa.array(vals, pa.int64())})
    raw = write(t, use_dictionary=False)
    host = decode.read_table(raw)          # oracle BEFORE the poison

    def boom(*a, **k):
        raise AssertionError("host RLE decode reached")
    monkeypatch.setattr(device_scan.D, "decode_rle_bitpacked_hybrid", boom)
    dev = device_scan.scan_table(raw)
    va = np.asarray(dev.columns[0].validity_or_true())
    np.testing.assert_array_equal(
        va, np.asarray(host.columns[0].validity_or_true()))
    np.testing.assert_array_equal(
        np.asarray(dev.columns[0].data)[va],
        np.asarray(host.columns[0].data)[va])


def test_rle_device_differential():
    """rle_device expansion (host + device) vs the host hybrid decoder
    across synthesized run mixes."""
    from spark_rapids_jni_tpu.parquet import rle_device as R

    def varint(v):
        out = b""
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                out += bytes([b | 0x80])
            else:
                return out + bytes([b])

    def bp(vals, bw):
        g = -(-len(vals) // 8)
        vals = list(vals) + [0] * (g * 8 - len(vals))
        bits = []
        for v in vals:
            bits += [(v >> i) & 1 for i in range(bw)]
        by = np.packbits(np.array(bits, np.uint8),
                         bitorder="little").tobytes()
        return varint((g << 1) | 1) + by

    def rle(val, cnt, bw):
        return varint(cnt << 1) + int(val).to_bytes((bw + 7) // 8,
                                                    "little")

    rng = np.random.default_rng(5)
    for bw in (1, 3, 8, 17, 24):
        n = 777
        vals = rng.integers(0, 1 << bw, n)
        buf = bp(vals, bw)
        plan = R.parse_runs(buf, bw, n)
        want = decode.decode_rle_bitpacked_hybrid(buf, bw, n)
        np.testing.assert_array_equal(R.expand_np(plan), want)
        np.testing.assert_array_equal(np.asarray(R.expand_device(plan)),
                                      want.astype(np.int32))
    # mixed runs + bucketed-R padding path
    buf = rle(2, 100, 3) + bp(rng.integers(0, 8, 64), 3) + rle(5, 33, 3)
    n = 197
    plan = R.parse_runs(buf, 3, n)
    want = decode.decode_rle_bitpacked_hybrid(buf, 3, n)
    np.testing.assert_array_equal(np.asarray(R.expand_device(plan)),
                                  want.astype(np.int32))
    # over-wide bit width → host fallback signal
    assert R.parse_runs(b"", 25, 10) is None


@pytest.mark.slow
def test_dict_strings_mostly_empty():
    """Short/empty dictionary entries: the adaptive group size must keep
    the device path engaged (round-5 regression: g=8 blew the P cap)."""
    n = 4000
    vals = ["" if i % 3 else "ab" for i in range(n)]
    t = pa.table({"s": pa.array(vals, pa.string())})
    raw = write(t, use_dictionary=True)
    dev = device_scan.scan_table(raw)
    host = decode.read_table(raw)
    _str_cols_equal(dev.columns[0], host.columns[0])


@pytest.mark.slow
def test_fused_scan_matches_per_column(monkeypatch):
    """The per-file fused program must produce exactly what the
    per-column dispatches produce."""
    n = 4000
    vals = [None if RNG.random() < 0.1 else f"w{i % 23}" for i in range(n)]
    t = pa.table({
        "s": pa.array(vals, pa.string()),
        "v": pa.array(RNG.integers(0, 9, n), pa.int64()),
        "f": pa.array(RNG.standard_normal(n), pa.float64()),
        "b": pa.array(RNG.integers(0, 2, n) > 0),
    })
    raw = write(t, compression="SNAPPY", use_dictionary=True)
    fused = device_scan.scan_table(raw)
    monkeypatch.setenv("SRJT_FUSED_SCAN", "0")
    percol = device_scan.scan_table(raw)
    assert_tables_match(fused, percol)
