"""DECIMAL128 lane-pair arithmetic, differential vs Python big-int oracle.

The reference gets __int128 fixed_point columns from libcudf (SURVEY §2.9);
here the payload is [n,2] int64 lanes with explicit limb arithmetic
(ops/decimal128.py).  Every op is checked against exact Python integers,
reduced mod 2^128 into the signed range.
"""

import random

import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_jni_tpu import types as T
from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.ops import cast, decimal128 as d128, groupby_aggregate
from spark_rapids_jni_tpu.ops.sort import sort_table

_TWO127 = 1 << 127


def _signed_mod(v: int) -> int:
    """Reduce an int into the signed 128-bit range (two's complement)."""
    v &= (1 << 128) - 1
    return v - (1 << 128) if v >= _TWO127 else v


def _rand_ints(n, bits=120, seed=0):
    rng = random.Random(seed)
    out = []
    for _ in range(n):
        mag = rng.getrandbits(rng.randrange(1, bits))
        out.append(mag if rng.random() < 0.5 else -mag)
    return out


class TestRoundTrip:
    def test_small_and_large(self):
        vals = [0, 1, -1, 2**64, -(2**64), 2**127 - 1, -(2**127), None, 12345]
        col = d128.from_pyints(vals)
        assert col.to_pylist() == vals

    def test_random(self):
        vals = _rand_ints(200)
        assert d128.from_pyints(vals).to_pylist() == vals


class TestArithmetic:
    def test_add_sub(self):
        a_v, b_v = _rand_ints(300, seed=1), _rand_ints(300, seed=2)
        a, b = d128.from_pyints(a_v), d128.from_pyints(b_v)
        got = d128.add(a, b).to_pylist()
        assert got == [_signed_mod(x + y) for x, y in zip(a_v, b_v)]
        got = d128.sub(a, b).to_pylist()
        assert got == [_signed_mod(x - y) for x, y in zip(a_v, b_v)]

    def test_add_null_propagation(self):
        a = d128.from_pyints([1, None, 3])
        b = d128.from_pyints([10, 20, None])
        assert d128.add(a, b).to_pylist() == [11, None, None]

    def test_negate(self):
        vals = _rand_ints(100, seed=3) + [0, -(2**127)]
        got = d128.negate(d128.from_pyints(vals)).to_pylist()
        assert got == [_signed_mod(-v) for v in vals]

    def test_mul_int(self):
        a_v = _rand_ints(200, bits=100, seed=4)
        b_v = [random.Random(5).randrange(-2**62, 2**62) for _ in a_v]
        a = d128.from_pyints(a_v)
        b = Column.from_numpy(np.asarray(b_v, np.int64))
        got = d128.mul_int(a, b).to_pylist()
        assert got == [_signed_mod(x * y) for x, y in zip(a_v, b_v)]

    def test_mul_full(self):
        a_v, b_v = _rand_ints(200, seed=6), _rand_ints(200, seed=7)
        a = d128.from_pyints(a_v, scale=-2)
        b = d128.from_pyints(b_v, scale=-3)
        res = d128.mul(a, b)
        assert res.dtype.scale == -5
        assert res.to_pylist() == [_signed_mod(x * y)
                                   for x, y in zip(a_v, b_v)]

    def test_rescale(self):
        vals = _rand_ints(50, bits=60, seed=8)
        col = d128.from_pyints(vals, scale=0)
        out = d128.rescale(col, -11)
        assert out.dtype.scale == -11
        assert out.to_pylist() == [_signed_mod(v * 10**11) for v in vals]

    def test_rescale_down_rounds_half_away(self):
        col = d128.from_pyints([12345, 12344, -12345, -12344, 2**100 + 50],
                               scale=-2)
        out = d128.rescale(col, -1)
        assert out.dtype.scale == -1
        assert out.to_pylist() == [1235, 1234, -1235, -1234,
                                   (2**100 + 50 + 5) // 10]

    def test_rescale_down_large_k(self):
        vals = _rand_ints(50, bits=120, seed=20)
        col = d128.from_pyints(vals, scale=0)
        out = d128.rescale(col, 25)
        d = 10**25
        want = [(abs(v) + d // 2) // d * (1 if v >= 0 else -1) for v in vals]
        assert out.to_pylist() == want


class TestReductions:
    def test_sum(self):
        vals = _rand_ints(500, seed=9)
        got = d128.sum_(d128.from_pyints(vals)).to_pylist()
        assert got == [_signed_mod(sum(vals))]

    def test_sum_skips_nulls(self):
        vals = [5, None, 7, None, -2]
        got = d128.sum_(d128.from_pyints(vals)).to_pylist()
        assert got == [10]

    def test_segmented_sum(self):
        vals = _rand_ints(100, seed=10)
        seg = np.sort(np.random.RandomState(0).randint(0, 5, size=100))
        col = d128.from_pyints(vals)
        got = d128.segmented_sum(col, jnp.asarray(seg), 5).to_pylist()
        want = [_signed_mod(sum(v for v, s in zip(vals, seg) if s == g))
                for g in range(5)]
        assert got == want


class TestCompareSort:
    def test_less_than(self):
        a_v, b_v = _rand_ints(300, seed=11), _rand_ints(300, seed=12)
        a, b = d128.from_pyints(a_v), d128.from_pyints(b_v)
        got = d128.less_than(a, b).to_pylist()
        assert got == [x < y for x, y in zip(a_v, b_v)]

    def test_sort(self):
        vals = _rand_ints(200, seed=13)
        t = sort_table(Table([d128.from_pyints(vals)]), [0])
        assert t[0].to_pylist() == sorted(vals)
        t = sort_table(Table([d128.from_pyints(vals)]), [0], ascending=[False])
        assert t[0].to_pylist() == sorted(vals, reverse=True)


class TestCasts:
    def test_widen_int64(self):
        vals = [0, 1, -1, 2**62, -(2**62), None]
        col = Column.from_numpy(np.asarray([0 if v is None else v for v in vals],
                                           np.int64),
                                validity=np.asarray([v is not None for v in vals]))
        got = cast(col, T.decimal128(0)).to_pylist()
        assert got == vals

    def test_widen_decimal64_rescale(self):
        col = Column.from_numpy(np.asarray([123, -45], np.int64),
                                T.decimal64(-2))
        out = cast(col, T.decimal128(-4))
        assert out.to_pylist() == [12300, -4500]

    def test_narrow_back(self):
        col = d128.from_pyints([123456, -789], scale=-2)
        out = cast(col, T.decimal64(-2))
        assert out.dtype == T.decimal64(-2)
        assert out.to_pylist() == [123456, -789]

    def test_to_float64(self):
        col = d128.from_pyints([12345, -67890, 2**70], scale=-2)
        got = cast(col, T.float64).to_numpy()
        want = np.asarray([123.45, -678.90, float(2**70) * 1e-2])
        np.testing.assert_allclose(got, want, rtol=1e-12)

    def test_float_to_decimal128(self):
        col = Column.from_numpy(np.asarray([1.25, -3.5], np.float64))
        got = cast(col, T.decimal128(-2)).to_pylist()
        assert got == [125, -350]

    def test_float_to_decimal128_large(self):
        # values whose scaled magnitude exceeds 2^63 must not wrap; the
        # result is the exact integer value of the (nearest-double) input
        col = Column.from_numpy(np.asarray([1e20, -1e24, 1e30], np.float64))
        got = cast(col, T.decimal128(0)).to_pylist()
        assert got == [int(np.float64(1e20)), -int(np.float64(1e24)),
                       int(np.float64(1e30))]

    def test_uint64_above_2_63_widens_unsigned(self):
        col = Column.from_numpy(np.asarray([2**63, 2**64 - 1], np.uint64))
        got = cast(col, T.decimal128(0)).to_pylist()
        assert got == [2**63, 2**64 - 1]

    def test_narrow_scale_reduction(self):
        # decimal128(-2) → decimal64(0) divides with round-half-away,
        # matching the decimal64 _rescale path
        col = d128.from_pyints([12345, -12355], scale=-2)
        out = cast(col, T.decimal64(0))
        assert out.to_pylist() == [123, -124]


class TestGroupby:
    def test_groupby_sum_decimal128(self):
        keys = Column.from_numpy(np.asarray([1, 2, 1, 2, 1], np.int32))
        vals = d128.from_pyints([2**100, 5, 2**100, -6, 1])
        out = groupby_aggregate(Table([keys, vals]), [0], [(1, "sum")])
        assert out[0].to_pylist() == [1, 2]
        assert out[1].to_pylist() == [_signed_mod(2**101 + 1), -1]

    def test_groupby_decimal128_non_sum_rejected(self):
        keys = Column.from_numpy(np.asarray([1], np.int32))
        vals = d128.from_pyints([1])
        with pytest.raises(NotImplementedError):
            groupby_aggregate(Table([keys, vals]), [0], [(1, "min")])


class TestJcudfRows:
    """DECIMAL128 in JCUDF rows (libcudf treats it as fixed-width; the
    framework packs it as two 64-bit words, 8-byte aligned)."""

    def _table(self, n=257, seed=4, with_strings=False):
        rng = np.random.default_rng(seed)
        vals = _rand_ints(n, bits=120, seed=seed)
        cols = [
            Column.from_numpy(rng.integers(-100, 100, n).astype(np.int32),
                              validity=rng.random(n) < 0.9),
            d128.from_pyints([None if rng.random() < 0.1 else v
                              for v in vals], scale=-2),
            Column.from_numpy(rng.integers(0, 2, n).astype(np.uint8),
                              T.bool8),
        ]
        if with_strings:
            cols.append(Column.strings_from_list(
                [None if rng.random() < 0.1 else f"s{i%37}"
                 for i in range(n)]))
        return Table(cols)

    def test_layout(self):
        from spark_rapids_jni_tpu.rowconv.layout import compute_row_layout
        lo = compute_row_layout([T.int32, T.decimal128(-2), T.bool8])
        assert lo.column_sizes == (4, 16, 1)
        # align-to-size, like every fixed-width slot in the reference
        # (row_conversion.cu:1331-1370)
        assert lo.column_starts == (0, 16, 32)

    def test_roundtrip_vs_oracle_fixed(self):
        from spark_rapids_jni_tpu.rowconv import (convert_to_rows,
                                                  convert_from_rows)
        from spark_rapids_jni_tpu.rowconv import reference as ref
        t = self._table()
        batches = convert_to_rows(t)
        ob, _ = ref.to_rows_np(t)
        np.testing.assert_array_equal(batches[0].host_bytes(), ob)
        back = convert_from_rows(batches[0], t.schema)
        assert back[1].dtype == T.decimal128(-2)
        assert back[1].to_pylist() == t[1].to_pylist()
        assert back[0].to_pylist() == t[0].to_pylist()

    @pytest.mark.slow
    def test_roundtrip_with_strings(self):
        from spark_rapids_jni_tpu.rowconv import (convert_to_rows,
                                                  convert_from_rows)
        from spark_rapids_jni_tpu.rowconv import reference as ref
        t = self._table(101, seed=5, with_strings=True)
        batches = convert_to_rows(t)
        ob, _ = ref.to_rows_np(t)
        np.testing.assert_array_equal(batches[0].host_bytes(), ob)
        back = convert_from_rows(batches[0], t.schema)
        for i in range(t.num_columns):
            assert back[i].to_pylist() == t[i].to_pylist(), i

    def test_oracle_roundtrip(self):
        from spark_rapids_jni_tpu.rowconv import reference as ref
        t = self._table(64, seed=6)
        rb, ro = ref.to_rows_np(t)
        back = ref.from_rows_np(rb, ro, list(t.schema))
        for i in range(t.num_columns):
            assert back[i].to_pylist() == t[i].to_pylist(), i

    def test_groupby_count_and_nunique_on_decimal128(self):
        keys = Column.from_numpy(np.asarray([1, 1, 2], np.int32))
        vals = d128.from_pyints([2**90, None, 5])
        out = groupby_aggregate(Table([keys, vals]), [0], [(1, "count")])
        assert out[1].to_pylist() == [1, 1]
        from spark_rapids_jni_tpu.ops import groupby_nunique
        dup = d128.from_pyints([2**90, 2**90, 5, 7])
        k2 = Column.from_numpy(np.asarray([1, 1, 1, 2], np.int32))
        nu = groupby_nunique(Table([k2, dup]), [0], 1)
        assert nu[1].to_pylist() == [2, 1]

    def test_groupby_var_on_string_raises_cleanly(self):
        keys = Column.from_numpy(np.asarray([1], np.int32))
        s = Column.strings_from_list(["a"])
        with pytest.raises(NotImplementedError, match="STRING"):
            groupby_aggregate(Table([keys, s]), [0], [(1, "var")])
