"""Chaos tests: multi-device serving under injected device faults.

Runs under the project-standard 8 forced-host devices (conftest).  The
contract under chaos extends the exec/ correctness contract: a fatal
device fault mid-run loses NO requests — they fail over to healthy
replicas and resolve bit-identical to serial execution; the victim
replica walks quarantine → probation → recovery (or ejection after
repeated probe failures); and everything joins in bounded time.

Determinism note: which replica serves first on a 1-core host is thread-
wakeup order, so device-targeted fault schedules first DISCOVER the
serving device (one probe request) and then arm the rule at it.
"""

import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spark_rapids_jni_tpu import exec as xc
from spark_rapids_jni_tpu import types as T
from spark_rapids_jni_tpu.column import Column, DictColumn, Table
from spark_rapids_jni_tpu.exec.placement import Replica, device_name
from spark_rapids_jni_tpu.faultinj import injector as finj
from spark_rapids_jni_tpu.faultinj.resilience import DeviceQuarantined
from spark_rapids_jni_tpu.utils import flight, metrics


@pytest.fixture(autouse=True)
def _chaos_env():
    metrics.set_enabled(True)
    metrics.reset()
    flight.reset()
    yield
    finj.get_injector().disable()
    metrics.reset()
    metrics.set_enabled(None)


def _mktab(n, seed):
    rng = np.random.default_rng(seed)
    return Table([Column(T.DType(T.TypeId.INT32),
                         jnp.asarray(rng.integers(0, 100, n, dtype=np.int32))),
                  Column(T.DType(T.TypeId.INT32),
                         jnp.asarray(rng.integers(0, 7, n, dtype=np.int32)))])


def _q_sum(tbls):
    t = tbls["t"]
    return Table([Column(T.DType(T.TypeId.INT64),
                         jnp.sum(t.columns[0].data.astype(jnp.int64))
                         .reshape(1))])


def _canon(result):
    return [np.asarray(l) for l in jax.tree_util.tree_leaves(result)]


def _same(a, b):
    return len(a) == len(b) and all(
        x.shape == y.shape and np.array_equal(x, y) for x, y in zip(a, b))


def _incident_kinds():
    return {e["kind"] for e in flight.events()
            if e["kind"].startswith("incident:")}


def _wait_replica(sched, index, pred, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        snap = sched.ops_state()["replicas"][index]
        if pred(snap):
            return snap
        time.sleep(0.02)
    return sched.ops_state()["replicas"][index]


# --- the headline chaos run --------------------------------------------------


def test_fatal_fault_mid_run_failover_bit_identical():
    """One-shot fatal fault on the serving device mid-run: every request
    still resolves, bit-identical to serial; the victim quarantines,
    requests fail over, and the recovery probe re-admits it."""
    assert len(jax.devices()) >= 4
    tables = {"t": _mktab(4096, 0)}
    oracle = _canon(_q_sum(tables))
    inj = finj.get_injector()
    t_start = time.monotonic()
    with xc.QueryScheduler(workers=4, devices=4, probe_base_s=0.02,
                           probe_max_s=0.2) as sched:
        # one-shot untargeted kill: whichever replica serves the next
        # request faults fatally (which one is thread-wakeup order; the
        # victim is discovered afterwards from replica state)
        inj.load_dict({"seed": 1, "sites": {
            "exec.dispatch": {"percent": 100,
                              "injectionType": "device_error",
                              "maxHits": 1}}})
        inj.enable()
        tickets = [sched.submit("q", _q_sum, tables) for _ in range(16)]
        for tk in tickets:
            assert _same(_canon(tk.result(timeout=120)), oracle), \
                "request lost or corrupted under chaos"
        # the fault fired exactly once and took exactly one replica down
        assert inj.injected_count == 1
        vi = next(i for i, r in enumerate(sched.replicas)
                  if r.resilient.fatal_count >= 1)
        snap = _wait_replica(
            sched, vi,
            lambda s: s["state"] == "healthy" and s["recoveries"] >= 1)
        assert snap["state"] == "healthy", snap
        assert snap["fatal_faults"] == 1 and snap["recoveries"] == 1, snap
        # at least one request relocated off the victim, and relocated
        # requests record their failover hop on the ticket
        relocated = [tk for tk in tickets if tk.relocations > 0]
        assert relocated, "no request failed over"
        counters = metrics.snapshot()["counters"]
        assert counters.get("exec.failover.relocated", 0) >= 1
        assert counters.get("exec.failover.recovered", 0) >= 1
        kinds = _incident_kinds()
        assert {"incident:quarantine", "incident:failover",
                "incident:recovery"} <= kinds, kinds
    # bounded-time join: chaos must not wedge shutdown
    assert time.monotonic() - t_start < 90


def test_multi_device_routing_spreads_load():
    """Independent slow requests spread across replicas (least-loaded is
    emergent: busy workers don't pull), and per-device completion
    counters account for every response."""
    tables = {"t": _mktab(512, 1)}

    def slow(tbls):
        time.sleep(0.03)
        return _q_sum(tbls)

    with xc.QueryScheduler(workers=4, devices=4, coalesce_ms=0) as sched:
        tickets = [sched.submit("slow", slow, tables, compiled=False)
                   for _ in range(16)]
        for tk in tickets:
            tk.result(timeout=120)
        used = {tk.device for tk in tickets}
        assert len(used) >= 2, f"all requests pinned to {used}"
        counters = metrics.snapshot()["counters"]
        per_dev = {r.name: counters.get(
            "exec.device." + r.name.replace(":", "") + ".completed", 0)
            for r in sched.replicas}
        assert sum(per_dev.values()) == 16, per_dev


def test_ejection_after_repeated_probe_failures():
    """A persistently-faulting device fails its recovery canaries and is
    permanently ejected; the rest of the pool keeps serving."""
    tables = {"t": _mktab(1024, 2)}
    oracle = _canon(_q_sum(tables))
    inj = finj.get_injector()
    # probe_base large enough that the first canary fires AFTER the
    # device-targeted kill rule below is armed (re-arm takes <50 ms)
    with xc.QueryScheduler(workers=2, devices=2, probe_base_s=0.5,
                           probe_max_s=0.6, eject_after=2) as sched:
        # step 1: one-shot untargeted fault downs whichever replica
        # serves; step 2: pin an UNLIMITED rule to that device so its
        # recovery canaries keep failing until ejection
        inj.load_dict({"seed": 1, "sites": {
            "exec.dispatch": {"percent": 100,
                              "injectionType": "device_error",
                              "maxHits": 1}}})
        inj.enable()
        tickets = [sched.submit("q", _q_sum, tables) for _ in range(6)]
        for tk in tickets:
            assert _same(_canon(tk.result(timeout=120)), oracle)
        vi = next(i for i, r in enumerate(sched.replicas)
                  if r.resilient.fatal_count >= 1)
        victim = sched.replicas[vi].name
        inj.load_dict({"seed": 1, "sites": {
            "exec.dispatch": {"percent": 100,
                              "injectionType": "device_error",
                              "device": victim}}})
        snap = _wait_replica(sched, vi,
                             lambda s: s["state"] == "ejected")
        assert snap["state"] == "ejected", snap
        counters = metrics.snapshot()["counters"]
        assert counters.get("exec.failover.probe_failed", 0) >= 2
        assert counters.get("exec.failover.ejected", 0) == 1
        assert "incident:ejected" in _incident_kinds()
        # the survivor still serves after the ejection
        inj.disable()
        tk = sched.submit("q", _q_sum, tables)
        assert _same(_canon(tk.result(timeout=60)), oracle)
        assert tk.device != victim


def test_whole_pool_quarantined_fails_fast_and_drains():
    """recovery=False pins the legacy terminal-quarantine contract at
    pool scope: once every replica is down, queued requests drain with
    a typed error and later submits fail fast."""
    tables = {"t": _mktab(256, 3)}
    inj = finj.get_injector()
    inj.load_dict({"seed": 1, "sites": {
        "exec.dispatch": {"percent": 100,
                          "injectionType": "device_error"}}})
    inj.enable()
    with xc.QueryScheduler(workers=2, devices=2, recovery=False,
                           coalesce_ms=0) as sched:
        tickets = [sched.submit("q", _q_sum, tables) for _ in range(8)]
        failures = 0
        for tk in tickets:
            with pytest.raises(DeviceQuarantined):
                tk.result(timeout=60)
            failures += 1
        assert failures == 8            # drained, not wedged
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            try:
                sched.submit("after", _q_sum, tables)
            except DeviceQuarantined:
                break
            time.sleep(0.01)
        else:
            pytest.fail("pool-wide quarantine did not fail fast")


# --- placement ---------------------------------------------------------------


def test_placement_replicates_and_caches():
    """Replica.place moves every buffer to its device, bit-identical,
    preserving DictColumn structure (codes + dictionary, no
    materialization), and identity-caches repeat placements."""
    devs = jax.devices()
    assert len(devs) >= 4
    rep = Replica(3, devs[3])
    chars = np.frombuffer(b"aabbbcc", dtype=np.uint8)
    dcol = Column(T.string, jnp.asarray(chars),
                  jnp.asarray([0, 2, 5, 7], jnp.int32))
    codes = jnp.asarray([2, 0, 1, 1, 0], jnp.int32)
    tab = Table([Column(T.DType(T.TypeId.INT32),
                        jnp.arange(5, dtype=jnp.int32)),
                 DictColumn(codes, dcol, sorted_dict=True)])
    placed = rep.place({"t": tab})["t"]
    assert isinstance(placed.columns[1], DictColumn), \
        "placement materialized the dict column"
    assert placed.columns[1].sorted_dict
    for arr in (placed.columns[0].data, placed.columns[1].codes,
                placed.columns[1].dictionary.data):
        assert arr.devices() == {devs[3]}, arr.devices()
    np.testing.assert_array_equal(np.asarray(placed.columns[0].data),
                                  np.arange(5, dtype=np.int32))
    np.testing.assert_array_equal(np.asarray(placed.columns[1].codes),
                                  np.asarray(codes))
    # identity cache: placing the same source buffers again reuses the
    # same device copies (stable plan-cache fingerprints per device)
    placed2 = rep.place({"t": tab})["t"]
    assert placed2.columns[0].data is placed.columns[0].data
    assert placed2.columns[1].codes is placed.columns[1].codes
    counters = metrics.snapshot()["counters"]
    assert counters.get("exec.place.hit", 0) >= 1
    assert counters.get("exec.place.copy", 0) >= 1


def test_placement_scope_sets_device_identity():
    devs = jax.devices()
    rep = Replica(2, devs[2])
    assert rep.name == device_name(devs[2])
    with rep.scope():
        assert finj.current_device() == rep.name
    assert finj.current_device() is None


# --- prefetch slot hygiene under failures ------------------------------------


def test_prefetch_slot_discarded_on_queue_deadline():
    """A loader-backed request that dies at its queue deadline must free
    its staged slot (exec.prefetch.discarded) instead of pinning
    double-buffer capacity forever."""
    tables = {"t": _mktab(256, 4)}

    def blocker_q(tbls):
        time.sleep(0.3)
        return _q_sum(tbls)

    with xc.QueryScheduler(workers=1, devices=1, coalesce_ms=0) as sched:
        blocker = sched.submit("blocker", blocker_q, tables,
                               compiled=False)
        doomed = sched.submit("doomed", _q_sum,
                              loader=lambda: tables, timeout_s=0.01,
                              compiled=False)
        with pytest.raises(xc.ExecDeadlineExceeded):
            doomed.result(timeout=60)
        blocker.result(timeout=60)
        counters = metrics.snapshot()["counters"]
        assert counters.get("exec.prefetch.discarded", 0) >= 1, counters
