"""Rewrite-engine tests: fixpoint termination, pushdown-through-join
correctness on a crafted schema, join-reorder behavior with/without
cardinality stats, fingerprint stability, and statistics-driven row-group
pruning end-to-end through the parquet scanner."""

import io

import numpy as np
import pytest

from spark_rapids_jni_tpu import plan as P
from spark_rapids_jni_tpu import types as T
from spark_rapids_jni_tpu.column import Column, Table, force_column
from spark_rapids_jni_tpu.plan import ir
from spark_rapids_jni_tpu.utils import metrics

SCHEMAS = {
    "fact": ["f_d1_sk", "f_d2_sk", "f_qty", "f_price", "f_pad"],
    "dim1": ["d1_sk", "d1_group", "d1_tag"],
    "dim2": ["d2_sk", "d2_group", "d2_tag"],
}


def _col(arr):
    return Column.from_numpy(np.asarray(arr))


@pytest.fixture(scope="module")
def tables():
    rng = np.random.default_rng(3)
    n = 4000
    fact = Table([
        _col(rng.integers(1, 40, n).astype(np.int32)),    # f_d1_sk
        _col(rng.integers(1, 25, n).astype(np.int32)),    # f_d2_sk
        _col(rng.integers(1, 9, n).astype(np.int64)),     # f_qty
        _col(rng.integers(1, 1000, n).astype(np.int64)),  # f_price
        _col(rng.integers(0, 2, n).astype(np.int32)),     # f_pad
    ])
    dim1 = Table([
        _col(np.arange(1, 40, dtype=np.int32)),           # d1_sk
        _col((np.arange(1, 40) % 5).astype(np.int32)),    # d1_group
        _col((np.arange(1, 40) % 7).astype(np.int32)),    # d1_tag
    ])
    dim2 = Table([
        _col(np.arange(1, 25, dtype=np.int32)),           # d2_sk
        _col((np.arange(1, 25) % 3).astype(np.int32)),    # d2_group
        _col((np.arange(1, 25) % 4).astype(np.int32)),    # d2_tag
    ])
    return {"fact": fact, "dim1": dim1, "dim2": dim2}


def _two_dim_tree():
    j = ir.Join(ir.Join(ir.Scan("fact"), ir.Scan("dim1"),
                        ("f_d1_sk",), ("d1_sk",)),
                ir.Scan("dim2"), ("f_d2_sk",), ("d2_sk",))
    f = ir.Filter(j, ir.And((
        ir.Cmp("==", ir.Col("d1_group"), ir.Lit(2)),
        ir.Cmp("==", ir.Col("d2_group"), ir.Lit(1)))))
    return ir.Sort(ir.Aggregate(f, ("d1_tag", "d2_tag"),
                                (("f_qty", "sum", "total_qty"),)),
                   ("d1_tag", "d2_tag"))


def _rows(table):
    cols = [force_column(c).to_numpy().tolist() for c in table]
    return sorted(zip(*cols)) if cols else []


def test_fixpoint_terminates_and_is_idempotent():
    res = P.optimize(_two_dim_tree(), SCHEMAS)
    assert res.converged
    assert res.passes <= 10
    assert res.events                      # something fired
    # re-optimizing the optimized tree is a no-op
    res2 = P.optimize(res.tree, SCHEMAS)
    assert res2.converged
    assert not res2.events
    assert res2.tree is res.tree or ir.fingerprint(res2.tree) == \
        ir.fingerprint(res.tree)


def test_pushdown_through_join_structure_and_results(tables):
    res = P.optimize(_two_dim_tree(), SCHEMAS)
    # both conjuncts reached their scans
    scans = {n.table: n for n in ir.walk(res.tree)
             if isinstance(n, ir.Scan)}
    assert scans["dim1"].predicate is not None
    assert scans["dim2"].predicate is not None
    assert "d1_group" in ir.expr_columns(scans["dim1"].predicate)
    # no Filter nodes survive above the joins
    assert not any(isinstance(n, ir.Filter) for n in ir.walk(res.tree))
    # projection narrowed the fact scan (f_pad, f_price unused)
    assert scans["fact"].columns is not None
    assert "f_pad" not in scans["fact"].columns
    # fusion detected
    assert any(isinstance(n, ir.FusedJoinAggregate)
               for n in ir.walk(res.tree))
    assert any(ev.rule == "fuse_join_aggregate" for ev in res.events)
    # optimized tree computes the same rows as the raw tree
    cat = P.TableCatalog(tables, SCHEMAS)
    raw = P.execute(_two_dim_tree(), cat, record_stats=False)
    opt = P.execute(res.tree, cat, record_stats=False)
    assert _rows(opt) == _rows(raw)


def test_join_reorder_noop_without_stats():
    tree = ir.Join(ir.Join(ir.Scan("fact"), ir.Scan("dim1"),
                           ("f_d1_sk",), ("d1_sk",)),
                   ir.Scan("dim2"), ("f_d2_sk",), ("d2_sk",))
    res = P.optimize(tree, SCHEMAS, stats=None)
    assert not any(ev.rule == "join_reorder" for ev in res.events)
    assert any(ev.rule == "join_reorder" for ev in res.rejections)
    assert ir.fingerprint(res.tree) == ir.fingerprint(tree)   # untouched
    # empty stats store: still a no-op (estimates unavailable)
    res2 = P.optimize(tree, SCHEMAS, stats=P.CardinalityStats())
    assert not any(ev.rule == "join_reorder" for ev in res2.events)
    assert any(ev.rule == "join_reorder" for ev in res2.rejections)


def test_join_reorder_fires_with_stats(tables):
    # plain two-join tree (no aggregate) so the reorder's row ordering
    # difference is visible and the Project-restored schema is checked
    tree = ir.Join(ir.Join(ir.Scan("fact"), ir.Scan("dim1"),
                           ("f_d1_sk",), ("d1_sk",)),
                   ir.Scan("dim2"), ("f_d2_sk",), ("d2_sk",))
    stats = P.CardinalityStats()
    # make dim2 look far smaller than dim1
    stats.observe(ir.fingerprint(ir.Scan("dim1")), 1000)
    stats.observe(ir.fingerprint(ir.Scan("dim2")), 3)
    res = P.optimize(tree, SCHEMAS, stats=stats)
    assert any(ev.rule == "join_reorder" for ev in res.events)
    assert isinstance(res.tree, ir.Project)     # column order restored
    assert ir.schema_of(res.tree, SCHEMAS) == ir.schema_of(tree, SCHEMAS)
    cat = P.TableCatalog(tables, SCHEMAS)
    raw = P.execute(tree, cat, record_stats=False)
    opt = P.execute(res.tree, cat, record_stats=False)
    # row ORDER legitimately changes with join order: compare as multisets
    assert _rows(opt) == _rows(raw)
    # and with reversed stats the rule stays quiet (already smallest-first)
    stats2 = P.CardinalityStats()
    stats2.observe(ir.fingerprint(ir.Scan("dim1")), 3)
    stats2.observe(ir.fingerprint(ir.Scan("dim2")), 1000)
    res2 = P.optimize(tree, SCHEMAS, stats=stats2)
    assert not any(ev.rule == "join_reorder" for ev in res2.events)


def test_executor_feeds_global_stats(tables):
    P.GLOBAL_STATS.clear()
    tree = ir.Join(ir.Scan("fact"), ir.Scan("dim1"),
                   ("f_d1_sk",), ("d1_sk",))
    out = P.execute(tree, P.TableCatalog(tables, SCHEMAS))
    assert P.GLOBAL_STATS.rows_for(tree) == float(out.num_rows)
    assert P.GLOBAL_STATS.rows_for(ir.Scan("fact")) == float(
        tables["fact"].num_rows)


def test_fingerprint_stability():
    t1, t2 = _two_dim_tree(), _two_dim_tree()
    assert t1 is not t2
    assert ir.fingerprint(t1) == ir.fingerprint(t2)
    # conjunct order and numpy-vs-python literals don't matter
    a = ir.Filter(ir.Scan("dim1"), ir.And((
        ir.Cmp("==", ir.Col("d1_group"), ir.Lit(2)),
        ir.Cmp("<", ir.Col("d1_tag"), ir.Lit(np.int64(5))))))
    b = ir.Filter(ir.Scan("dim1"), ir.And((
        ir.Cmp("<", ir.Col("d1_tag"), ir.Lit(5)),
        ir.Cmp("==", ir.Col("d1_group"), ir.Lit(2)))))
    assert ir.fingerprint(a) == ir.fingerprint(b)
    # semantic changes DO matter
    c = ir.Filter(ir.Scan("dim1"),
                  ir.Cmp("==", ir.Col("d1_group"), ir.Lit(3)))
    assert ir.fingerprint(a) != ir.fingerprint(c)


def test_schema_validation_errors():
    with pytest.raises(ir.PlanError):
        ir.schema_of(ir.Scan("nope"), SCHEMAS)
    with pytest.raises(ir.PlanError):
        ir.schema_of(ir.Filter(ir.Scan("dim1"),
                               ir.Cmp("==", ir.Col("bogus"), ir.Lit(1))),
                     SCHEMAS)
    with pytest.raises(ir.PlanError):   # join sides sharing names
        ir.schema_of(ir.Join(ir.Scan("dim1"), ir.Scan("dim1"),
                             ("d1_sk",), ("d1_sk",)), SCHEMAS)


def test_explain_renders_both_trees():
    text = P.explain(_two_dim_tree(), SCHEMAS)
    assert "== Logical plan ==" in text
    assert "== Optimized plan" in text
    assert "fired    filter_pushdown" in text
    assert "fired    fuse_join_aggregate" in text
    assert "FusedJoinAggregate" in text


def test_rowgroup_pruning_end_to_end():
    pa = pytest.importorskip("pyarrow")
    pq = pytest.importorskip("pyarrow.parquet")
    from spark_rapids_jni_tpu.parquet import device_scan

    n = 1000
    key = np.arange(n, dtype=np.int32)          # sorted: tight rg stats
    val = (key * 3).astype(np.int64)
    buf = io.BytesIO()
    pq.write_table(pa.table({"key": pa.array(key), "val": pa.array(val)}),
                   buf, use_dictionary=False, row_group_size=100)
    raw = buf.getvalue()

    metrics.set_enabled(True)
    metrics.reset()
    try:
        full = device_scan.scan_table(raw)
        pruned = device_scan.scan_table(
            raw, rowgroup_predicate=[("key", "eq", 250)])
        counters = metrics.snapshot()["counters"]
    finally:
        metrics.set_enabled(False)
    assert counters.get("plan.scan.rowgroups_pruned", 0) == 9
    assert counters.get("plan.scan.rowgroups_kept", 0) == 1
    assert full.num_rows == n
    assert pruned.num_rows == 100               # only the matching group
    got = pruned[0].to_numpy()
    assert got.min() == 200 and got.max() == 299
    np.testing.assert_array_equal(pruned[1].to_numpy(),
                                  got.astype(np.int64) * 3)
    # all groups pruned → empty table with the right schema
    empty = device_scan.scan_table(
        raw, rowgroup_predicate=[("key", "gt", 10_000)])
    assert empty.num_rows == 0
    assert empty.num_columns == 2
    # range conjuncts prune from both ends
    band = device_scan.scan_table(
        raw, rowgroup_predicate=[("key", "ge", 150), ("key", "lt", 350)])
    assert band.num_rows == 300                 # groups 1, 2, 3


def test_plan_disable_env(monkeypatch):
    monkeypatch.setenv("SRJT_PLAN_OPT", "0")
    res = P.optimize(_two_dim_tree(), SCHEMAS)
    assert not res.events and res.passes == 0
    monkeypatch.delenv("SRJT_PLAN_OPT")
    monkeypatch.setenv("SRJT_PLAN_RULES", "projection_pushdown")
    res2 = P.optimize(_two_dim_tree(), SCHEMAS)
    assert res2.events
    assert {ev.rule for ev in res2.events} == {"projection_pushdown"}
