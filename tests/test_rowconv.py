"""Row↔column conversion tests.

Mirrors the reference test strategy (SURVEY §4, tests/row_conversion.cpp):
- differential testing: JAX device path vs the NumPy oracle (the reference
  uses its legacy CUDA path as oracle, tests/row_conversion.cpp:49-58)
- round-trip testing: to_rows → from_rows → table equality (:204-218)
- shape/stress sweep incl. non-power-of-2 sizes (:221-437)
- type-matrix with validity patterns all/none/most/few (:546-707)
- string tests (:62-200, 825-1023)
"""

import numpy as np
import pytest

import spark_rapids_jni_tpu as sr
from spark_rapids_jni_tpu import Column, Table, convert_to_rows, convert_from_rows
from spark_rapids_jni_tpu.rowconv import reference as ref
from spark_rapids_jni_tpu.rowconv.convert import (
    convert_to_rows_fixed_width_optimized,
    convert_from_rows_fixed_width_optimized,
)

RNG = np.random.default_rng(42)


def random_validity(n, pattern):
    if pattern == "all":
        return None
    if pattern == "none":
        return np.zeros(n, dtype=bool)
    if pattern == "most":
        return RNG.random(n) < 0.9
    return RNG.random(n) < 0.1  # "few"


def random_column(dtype, n, validity="all"):
    v = random_validity(n, validity)
    if dtype.id == sr.TypeId.STRING:
        words = ["", "a", "spark", "tpu-native", "longer string payload 🎉",
                 "x" * 37]
        strs = [words[i % len(words)] for i in range(n)]
        col = Column.strings_from_list(strs)
        if v is not None:
            import jax.numpy as jnp
            col = Column(col.dtype, col.data, col.offsets, jnp.asarray(v))
        return col
    if dtype.id == sr.TypeId.BOOL8:
        arr = RNG.integers(0, 2, n).astype(np.uint8)
    elif dtype.storage.kind == "f":
        arr = RNG.standard_normal(n).astype(dtype.storage)
    else:
        info = np.iinfo(dtype.storage)
        arr = RNG.integers(info.min // 2, info.max // 2, n,
                           dtype=dtype.storage)
    return Column.from_numpy(arr, dtype, v)


def assert_tables_equal(a: Table, b: Table):
    assert a.num_columns == b.num_columns
    assert a.num_rows == b.num_rows
    for i, (ca, cb) in enumerate(zip(a.columns, b.columns)):
        assert ca.dtype == cb.dtype, f"col {i}"
        va = np.asarray(ca.validity_or_true())
        vb = np.asarray(cb.validity_or_true())
        np.testing.assert_array_equal(va, vb, err_msg=f"col {i} validity")
        if ca.dtype.id == sr.TypeId.STRING:
            # compare only valid rows' payloads
            la, lb = ca.to_pylist(), cb.to_pylist()
            assert [x for x, ok in zip(la, va) if ok] == \
                   [x for x, ok in zip(lb, vb) if ok], f"col {i}"
        else:
            da, db = np.asarray(ca.data), np.asarray(cb.data)
            np.testing.assert_array_equal(da[va], db[vb], err_msg=f"col {i}")


def roundtrip_and_differential(table: Table):
    """JAX path bytes == NumPy oracle bytes, and round-trip == identity."""
    batches = convert_to_rows(table)
    oracle_bytes, oracle_offsets = ref.to_rows_np(table)
    got = np.concatenate([b.host_bytes() for b in batches])
    np.testing.assert_array_equal(got, oracle_bytes)

    assert len(batches) == 1
    back = convert_from_rows(batches[0], table.schema)
    assert_tables_equal(table, back)

    # oracle round-trip too (the spec must be self-consistent)
    back_np = ref.from_rows_np(oracle_bytes, oracle_offsets, list(table.schema))
    assert_tables_equal(table, back_np)


# ---- fixed width ----------------------------------------------------------

def test_single_int64_column():
    roundtrip_and_differential(Table([random_column(sr.int64, 17)]))


def test_simple_mixed_fixed_width():
    t = Table([random_column(sr.int8, 31), random_column(sr.int32, 31),
               random_column(sr.float64, 31), random_column(sr.bool8, 31)])
    roundtrip_and_differential(t)


def test_tall_narrow():
    # Tall: 4096 × 1 (tests/row_conversion.cpp Tall analog)
    roundtrip_and_differential(Table([random_column(sr.int32, 4096)]))


@pytest.mark.slow
def test_wide_256_columns():
    t = Table([random_column(sr.int8, 13) for _ in range(256)])
    roundtrip_and_differential(t)


def test_non_power_of_two_shape():
    # alignment edge cases: 557 rows × 131 cols of cycling types
    kinds = [sr.int8, sr.int16, sr.int32, sr.int64, sr.float32]
    t = Table([random_column(kinds[i % len(kinds)], 557) for i in range(131)])
    roundtrip_and_differential(t)


@pytest.mark.parametrize("pattern", ["all", "none", "most", "few"])
def test_type_matrix_with_validity(pattern):
    n = 97
    dtypes = [sr.int8, sr.int16, sr.int32, sr.int64, sr.float32, sr.float64,
              sr.bool8, sr.timestamp_ms, sr.timestamp_days,
              sr.decimal32(-2), sr.decimal64(-4)]
    t = Table([random_column(dt, n, pattern) for dt in dtypes])
    roundtrip_and_differential(t)


def test_fixed_width_optimized_parity():
    t = Table([random_column(sr.int32, 64), random_column(sr.int64, 64)])
    a = convert_to_rows(t)[0]
    b = convert_to_rows_fixed_width_optimized(t)[0]
    np.testing.assert_array_equal(np.asarray(a.data), np.asarray(b.data))
    back = convert_from_rows_fixed_width_optimized(b, t.schema)
    assert_tables_equal(t, back)


def test_multi_batch_splitting():
    # force multiple ≤2GB-style batches with a tiny cap (Biggest analog)
    t = Table([random_column(sr.int64, 200)])
    batches = convert_to_rows(t, max_batch_bytes=1024)
    assert len(batches) > 1
    oracle_bytes, _ = ref.to_rows_np(t)
    got = np.concatenate([b.host_bytes() for b in batches])
    np.testing.assert_array_equal(got, oracle_bytes)
    # each batch independently converts back; rows concatenate in order
    lay_rows = []
    for b in batches:
        back = convert_from_rows(b, t.schema)
        lay_rows.append(back[0].to_numpy())
    np.testing.assert_array_equal(np.concatenate(lay_rows), t[0].to_numpy())


# ---- strings --------------------------------------------------------------

@pytest.mark.slow
def test_simple_string():
    t = Table([random_column(sr.int32, 11), random_column(sr.string, 11)])
    roundtrip_and_differential(t)


@pytest.mark.slow
def test_two_string_columns():
    t = Table([random_column(sr.string, 29), random_column(sr.int64, 29),
               random_column(sr.string, 29)])
    roundtrip_and_differential(t)


@pytest.mark.parametrize("pattern", ["most", "few"])
@pytest.mark.slow
def test_strings_with_nulls(pattern):
    t = Table([random_column(sr.string, 53, pattern),
               random_column(sr.int16, 53, pattern)])
    roundtrip_and_differential(t)


@pytest.mark.slow
def test_many_strings_mixed():
    n = 512
    cols = []
    for i in range(10):
        cols.append(random_column(sr.string if i % 3 == 0 else sr.int32, n,
                                  "most" if i % 2 else "all"))
    roundtrip_and_differential(Table(cols))


def test_empty_strings_only():
    c = Column.strings_from_list(["", "", ""])
    roundtrip_and_differential(Table([c, random_column(sr.int8, 3)]))


def test_zero_row_roundtrip():
    # empty partitions are routine in Spark shuffles
    t = Table([Column.from_numpy(np.zeros(0, np.int32)),
               Column.from_numpy(np.zeros(0, np.int64))])
    batches = convert_to_rows(t)
    back = convert_from_rows(batches[0], t.schema)
    assert back.num_rows == 0
    ts = Table([Column.strings_from_list([]),
                Column.from_numpy(np.zeros(0, np.int16))])
    batches = convert_to_rows(ts)
    back = convert_from_rows(batches[0], ts.schema)
    assert back.num_rows == 0


def test_fixed_batches_are_u32_words():
    # Fixed-width batches carry the JCUDF byte stream as u32 words (rows are
    # 8-byte aligned, so the view is exact); host_bytes() is the canonical
    # byte materialization and must match the scalar oracle.
    import jax.numpy as jnp
    t = Table([Column.from_numpy(np.arange(100, dtype=np.int32)),
               Column.from_numpy(np.arange(100, dtype=np.int16))])
    b = convert_to_rows(t)[0]
    assert b.data.dtype == jnp.uint32
    ob, _ = ref.to_rows_np(t)
    np.testing.assert_array_equal(b.host_bytes(), ob)
    # from_rows accepts the byte view of the same batch too
    from spark_rapids_jni_tpu.rowconv.convert import RowBatch
    back = convert_from_rows(RowBatch(b.device_u8(), b.offsets), t.schema)
    for a, c in zip(back.columns, t.columns):
        np.testing.assert_array_equal(np.asarray(a.data), np.asarray(c.data))


@pytest.mark.slow
def test_xpack_geometry_not_reused_across_layouts():
    """Round-4 regression: the xpack geometry memo is keyed on the string
    column's offsets arrays — REUSING the same string Column under a
    different fixed-width layout (different fpv → different row sizes)
    must re-plan, not hit a stale geometry and emit corrupt rows."""
    import os
    rng = np.random.default_rng(5)
    n = 3000
    strs = [("v" * int(k)) if k else "" for k in rng.integers(0, 9, n)]
    str_col = Column.strings_from_list(strs)
    t1 = Table([Column.from_numpy(
        rng.integers(0, 100, n, dtype=np.int32)), str_col])
    t2 = Table([Column.from_numpy(
        rng.integers(0, 100, n, dtype=np.int64)), str_col,
        Column.from_numpy(rng.integers(0, 2, n).astype(np.uint8),
                          sr.bool8)])
    for t in (t1, t2):
        got = convert_to_rows(t)[0].host_bytes()
        os.environ["SRJT_XPACK"] = "0"
        try:
            want = convert_to_rows(t)[0].host_bytes()
        finally:
            os.environ["SRJT_XPACK"] = "1"
        np.testing.assert_array_equal(got, want)


# ---- inverse xpack engine (round 5) ---------------------------------------

def _xpack_off():
    import contextlib, os

    @contextlib.contextmanager
    def ctx():
        os.environ["SRJT_XPACK"] = "0"
        try:
            yield
        finally:
            os.environ["SRJT_XPACK"] = "1"
    return ctx()


@pytest.mark.slow
def test_from_rows_xpack_differential():
    """The fused inverse engine must byte-match the non-xpack from_rows
    path (which matches the NumPy oracle) across geometries that stress
    the bucket planner: many short strings, a long outlier, nulls."""
    from spark_rapids_jni_tpu.rowconv import xpack
    rng = np.random.default_rng(11)
    for n in (5, 257, 4096):
        strs = [("s" * int(k)) if k else "" for k in rng.integers(0, 40, n)]
        strs[n // 2] = "y" * 300                  # Lw outlier
        t = Table([
            Column.strings_from_list(strs),
            random_column(sr.int64, n, "most"),
            Column.strings_from_list([s[::-1] for s in strs]),
            random_column(sr.int16, n, "few"),
        ])
        b = convert_to_rows(t)[0]
        layout_got = convert_from_rows(b, t.schema)
        with _xpack_off():
            want = convert_from_rows(b, t.schema)
        assert_tables_equal(layout_got, want)


@pytest.mark.slow
def test_from_rows_xpack_engages():
    """Regression: the engine must actually run (not silently fall back)
    on the bench-shaped geometry."""
    from spark_rapids_jni_tpu.rowconv import xpack
    rng = np.random.default_rng(3)
    n = 2048
    words = ["", "tpu", "spark-rapids", "columnar row transcode",
             "x" * 24, "payload"]
    t = Table([
        Column.from_numpy(rng.integers(0, 99, n, dtype=np.int32)),
        Column.strings_from_list(
            [words[j] for j in rng.integers(0, len(words), n)]),
    ])
    b = convert_to_rows(t)[0]
    layout = sr.rowconv.convert.compute_row_layout(t.schema)
    res = xpack.from_rows_var_x(layout, b)
    assert res is not None
    datas, valid, chars, out_offs = res
    np.testing.assert_array_equal(np.asarray(chars[0]),
                                  np.asarray(t[1].data))
    np.testing.assert_array_equal(np.asarray(out_offs[0]),
                                  np.asarray(t[1].offsets))


@pytest.mark.slow
def test_from_rows_xpack_corrupt_slot_raises():
    """Shuffle-received rows with an out-of-row slot must raise, not read
    out of bounds (host_table.cpp srjt_from_rows hardening parity)."""
    import jax.numpy as jnp
    from spark_rapids_jni_tpu.rowconv.convert import RowBatch
    n = 64
    t = Table([Column.from_numpy(np.arange(n, dtype=np.int32)),
               Column.strings_from_list(["abcd"] * n)])
    b = convert_to_rows(t)[0]
    u8 = np.array(b.host_bytes())
    # row 0: string slot starts at byte 8 (after i32 + slot... layout:
    # i32 @0, slot @8? — just blast the len field of the first slot huge
    layout = sr.rowconv.convert.compute_row_layout(t.schema)
    ci = layout.variable_column_indices[0]
    slot_start = layout.column_starts[ci]
    u8[slot_start + 4:slot_start + 8] = np.frombuffer(
        np.uint32(1 << 20).tobytes(), dtype=np.uint8)
    bad = RowBatch(jnp.asarray(u8), b.offsets)
    with pytest.raises(ValueError, match="corrupt row"):
        convert_from_rows(bad, t.schema)


@pytest.mark.slow
def test_xpack_fallback_accounting():
    """A geometry outside the packing caps must fall back AND say why."""
    from spark_rapids_jni_tpu.rowconv import xpack
    before = sum(xpack.fallback_counts.values())
    n = 40
    # 600-char strings: rows stay under the 1KB JCUDF cap, but a group of
    # 8 rows spans ~4.8KB of chars -> the from_rows dst-span bucket (Bd)
    # exceeds its 512-word cap and the engine must degrade with accounting
    strs = [("q" * 600) for _ in range(n)]
    t = Table([Column.strings_from_list(strs),
               Column.from_numpy(np.arange(n, dtype=np.int8), sr.int8)])
    b = convert_to_rows(t)[0]
    back = convert_from_rows(b, t.schema)
    np.testing.assert_array_equal(np.asarray(back[0].data),
                                  np.asarray(t[0].data))
    after = sum(xpack.fallback_counts.values())
    assert after > before, "fallback happened but was not accounted"


def test_fixed_concat_engine_differential(monkeypatch):
    """The round-5 concat compose (SRJT_FIXED_CONCAT=1) must be
    byte-identical to the perm3/word-compose engine on both directions,
    incl. decimal128 / f64-bit-pair / sub-word columns."""
    monkeypatch.delenv("SRJT_FIXED_CONCAT", raising=False)
    import bench as bench_mod
    t = bench_mod.build_table(10_000, 12)
    # the bench cycle has no decimal128: append one so the 16-byte quad
    # block compose/decode is covered
    import jax.numpy as jnp
    lanes = RNG.integers(-2**62, 2**62, (10_000, 2), dtype=np.int64)
    dec = Column(sr.types.decimal128(-2), jnp.asarray(lanes),
                 validity=jnp.asarray(RNG.random(10_000) < 0.9))
    t = Table(list(t.columns) + [dec])
    b_ref = convert_to_rows(t)[0]
    monkeypatch.setenv("SRJT_FIXED_CONCAT", "1")
    b_new = convert_to_rows(t)[0]
    np.testing.assert_array_equal(b_ref.host_bytes(), b_new.host_bytes())
    back = convert_from_rows(b_new, t.schema)
    monkeypatch.delenv("SRJT_FIXED_CONCAT")
    want = convert_from_rows(b_ref, t.schema)
    for a, c in zip(back.columns, want.columns):
        np.testing.assert_array_equal(np.asarray(a.data), np.asarray(c.data))
        np.testing.assert_array_equal(np.asarray(a.validity_or_true()),
                                      np.asarray(c.validity_or_true()))
