"""Structured-logging knob (SURVEY §5.5): @traced entries emit one event
record per call when SPARK_RAPIDS_TPU_LOG is on."""

import json

import numpy as np

from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.rowconv import convert_to_rows
from spark_rapids_jni_tpu.utils import structured_log as slog


def _run_traced_call():
    t = Table([Column.from_numpy(np.arange(8, dtype=np.int32))])
    convert_to_rows(t)


def test_off_by_default(tmp_path):
    p = tmp_path / "log.txt"
    slog.configure(mode="off", path=str(p))
    _run_traced_call()
    assert not p.exists() or p.read_text() == ""


def test_json_mode(tmp_path):
    p = tmp_path / "log.jsonl"
    slog.configure(mode="json", path=str(p))
    try:
        _run_traced_call()
    finally:
        slog.configure(mode="off")
    lines = [json.loads(x) for x in p.read_text().splitlines()]
    assert any(r["event"].startswith("convert_to_rows") for r in lines)
    rec = lines[0]
    assert "ts" in rec and rec["duration_ms"] >= 0


def test_text_mode_and_fields(tmp_path):
    p = tmp_path / "log.txt"
    slog.configure(mode="text", path=str(p))
    try:
        slog.event("custom", duration_s=0.5, rows=10)
    finally:
        slog.configure(mode="off")
    txt = p.read_text()
    assert "[srjt] custom" in txt and "500.000ms" in txt and "rows=10" in txt


def test_off_flip_closes_stream(tmp_path):
    p = tmp_path / "log.txt"
    slog.configure(mode="text", path=str(p))
    slog.event("one")
    assert slog._stream is not None and not slog._stream.closed
    slog.configure(mode="off")        # flip must close + reset the stream
    assert slog._stream is None
    slog.event("dropped")             # no-op — and must not reopen
    assert slog._stream is None
    assert "dropped" not in p.read_text()


def test_path_switch_reopens(tmp_path):
    a, b = tmp_path / "a.txt", tmp_path / "b.txt"
    slog.configure(mode="text", path=str(a))
    try:
        slog.event("first")
        slog.configure(path=str(b))   # close a, lazily open b on next write
        slog.event("second")
    finally:
        slog.configure(mode="off")
    assert "first" in a.read_text() and "second" not in a.read_text()
    assert "second" in b.read_text()


def test_event_survives_externally_closed_stream(tmp_path):
    p = tmp_path / "log.txt"
    slog.configure(mode="text", path=str(p))
    try:
        slog.event("one")
        slog._stream.close()          # simulate an external close
        slog.event("two")             # _out() must detect + reopen
    finally:
        slog.configure(mode="off")
    txt = p.read_text()
    assert "one" in txt and "two" in txt


def test_concurrent_events_during_reconfigure(tmp_path):
    """Writers racing configure() flips never hit a closed stream."""
    import threading

    p = tmp_path / "log.txt"
    errors = []

    def writer():
        for _ in range(200):
            try:
                slog.event("w", rows=1)
            except ValueError as e:     # "I/O operation on closed file"
                errors.append(e)

    def flipper():
        for i in range(100):
            slog.configure(mode="off" if i % 2 else "text", path=str(p))

    slog.configure(mode="text", path=str(p))
    threads = [threading.Thread(target=writer) for _ in range(4)]
    threads.append(threading.Thread(target=flipper))
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    slog.configure(mode="off")
    assert errors == []
