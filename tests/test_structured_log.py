"""Structured-logging knob (SURVEY §5.5): @traced entries emit one event
record per call when SPARK_RAPIDS_TPU_LOG is on."""

import json

import numpy as np

from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.rowconv import convert_to_rows
from spark_rapids_jni_tpu.utils import structured_log as slog


def _run_traced_call():
    t = Table([Column.from_numpy(np.arange(8, dtype=np.int32))])
    convert_to_rows(t)


def test_off_by_default(tmp_path):
    p = tmp_path / "log.txt"
    slog.configure(mode="off", path=str(p))
    _run_traced_call()
    assert not p.exists() or p.read_text() == ""


def test_json_mode(tmp_path):
    p = tmp_path / "log.jsonl"
    slog.configure(mode="json", path=str(p))
    try:
        _run_traced_call()
    finally:
        slog.configure(mode="off")
    lines = [json.loads(x) for x in p.read_text().splitlines()]
    assert any(r["event"].startswith("convert_to_rows") for r in lines)
    rec = lines[0]
    assert "ts" in rec and rec["duration_ms"] >= 0


def test_text_mode_and_fields(tmp_path):
    p = tmp_path / "log.txt"
    slog.configure(mode="text", path=str(p))
    try:
        slog.event("custom", duration_s=0.5, rows=10)
    finally:
        slog.configure(mode="off")
    txt = p.read_text()
    assert "[srjt] custom" in txt and "500.000ms" in txt and "rows=10" in txt
