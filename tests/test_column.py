import numpy as np
import pytest

import spark_rapids_jni_tpu as sr
from spark_rapids_jni_tpu import Column, Table


def test_fixed_width_column_roundtrip():
    arr = np.asarray([1, -2, 3], dtype=np.int32)
    c = Column.from_numpy(arr)
    assert c.dtype == sr.int32
    assert c.num_rows == 3 and c.null_count == 0
    np.testing.assert_array_equal(c.to_numpy(), arr)


def test_bool_column_stored_as_byte():
    c = Column.from_numpy(np.asarray([True, False, True]))
    assert c.dtype == sr.bool8
    assert c.data.dtype == np.uint8
    assert c.to_pylist() == [True, False, True]


def test_validity_and_null_count():
    c = Column.from_numpy(np.asarray([1, 2, 3, 4], dtype=np.int64),
                          validity=np.asarray([True, False, True, False]))
    assert c.null_count == 2
    assert c.to_pylist() == [1, None, 3, None]
    np.testing.assert_array_equal(np.asarray(c.validity_bitmask()), [0b0101])


def test_string_column():
    c = Column.strings_from_list(["hello", "", None, "wörld"])
    assert c.dtype == sr.string
    assert c.num_rows == 4
    assert c.null_count == 1
    assert c.to_pylist() == ["hello", "", None, "wörld"]


def test_table_basics_and_mismatch():
    t = Table.from_pydict({"a": [1, 2, 3], "s": ["x", "y", None]})
    assert t.num_columns == 2 and t.num_rows == 3
    assert t.schema[1] == sr.string
    with pytest.raises(ValueError):
        Table([Column.from_numpy(np.zeros(2, np.int32)),
               Column.from_numpy(np.zeros(3, np.int32))])


def test_table_is_a_pytree():
    import jax
    t = Table.from_pydict({"a": [1, 2, 3]})
    t2 = jax.tree_util.tree_map(lambda x: x, t)
    assert isinstance(t2, Table)
    np.testing.assert_array_equal(t2[0].to_numpy(), t[0].to_numpy())
