"""The pandas baseline plans (benchmarks/pandas_queries.py) must do the
same WORK as the framework queries: same coverage (every QUERIES entry)
and same result cardinality on shared data.  Exact-value correctness is
the per-query differentials' job (test_tpcds*.py); this guards the
baseline harness from timing a different plan."""

import io

import numpy as np
import pandas as pd
import pytest

from benchmarks import pandas_queries as PQ
from benchmarks import tpcds_data
from spark_rapids_jni_tpu.models import tpcds


@pytest.fixture(scope="module")
def data():
    files = tpcds_data.generate(n_sales=20_000, n_items=300, seed=11)
    dfs = {k: pd.read_parquet(io.BytesIO(v)) for k, v in files.items()}
    tables = tpcds.load_tables(files)
    return dfs, tables


def test_full_coverage():
    assert set(PQ.QUERIES) == set(tpcds.QUERIES)


@pytest.mark.slow
@pytest.mark.parametrize("qname", sorted(tpcds.QUERIES))
def test_same_cardinality(data, qname):
    dfs, tables = data
    out_pd = PQ.QUERIES[qname](dfs)
    out_fw = tpcds.QUERIES[qname](tables)
    assert len(out_pd) == out_fw.num_rows, (
        f"{qname}: pandas {len(out_pd)} rows vs framework "
        f"{out_fw.num_rows}")
