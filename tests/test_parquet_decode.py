"""Parquet decode tests: our reader vs pyarrow-written files (pyarrow is the
independent oracle for values)."""

import io

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_jni_tpu.parquet import decode
from spark_rapids_jni_tpu.models import q6

RNG = np.random.default_rng(11)


def write(table: pa.Table, **kw) -> bytes:
    buf = io.BytesIO()
    pq.write_table(table, buf, **kw)
    return buf.getvalue()


def test_plain_numeric_roundtrip():
    n = 5000
    t = pa.table({
        "i32": pa.array(RNG.integers(-1000, 1000, n, dtype=np.int32)),
        "i64": pa.array(RNG.integers(-10**12, 10**12, n, dtype=np.int64)),
        "f32": pa.array(RNG.standard_normal(n).astype(np.float32)),
        "f64": pa.array(RNG.standard_normal(n)),
        "b": pa.array(RNG.integers(0, 2, n).astype(bool)),
    })
    # disable dictionary to force PLAIN
    raw = write(t, compression="NONE", use_dictionary=False)
    got = decode.read_table(raw)
    for i, name in enumerate(t.column_names):
        expect = t[name].to_numpy()
        if name == "b":
            np.testing.assert_array_equal(
                got[i].to_numpy().astype(bool), expect)
        else:
            np.testing.assert_array_equal(got[i].to_numpy(), expect)


def test_dictionary_encoded_roundtrip():
    n = 3000
    t = pa.table({
        "k": pa.array(RNG.integers(0, 20, n, dtype=np.int64)),
        "f": pa.array(np.repeat(RNG.standard_normal(30), 100)),
    })
    raw = write(t, compression="NONE", use_dictionary=True)
    got = decode.read_table(raw)
    np.testing.assert_array_equal(got[0].to_numpy(), t["k"].to_numpy())
    np.testing.assert_array_equal(got[1].to_numpy(), t["f"].to_numpy())


def test_gzip_codec():
    n = 2000
    t = pa.table({"x": pa.array(RNG.integers(0, 100, n, dtype=np.int32))})
    raw = write(t, compression="GZIP", use_dictionary=False)
    got = decode.read_table(raw)
    np.testing.assert_array_equal(got[0].to_numpy(), t["x"].to_numpy())


def test_nullable_column():
    vals = [1, None, 3, None, 5] * 200
    t = pa.table({"x": pa.array(vals, type=pa.int64())})
    raw = write(t, compression="NONE", use_dictionary=False)
    got = decode.read_table(raw)
    assert got[0].to_pylist() == vals


def test_strings_plain_and_dict():
    strs = [f"value_{i % 7}" for i in range(1000)]
    t = pa.table({"s": pa.array(strs)})
    for use_dict in (False, True):
        raw = write(t, compression="NONE", use_dictionary=use_dict)
        got = decode.read_table(raw)
        assert got[0].to_pylist() == strs


def test_strings_with_nulls():
    strs = ["abc", None, "", "d" * 50, None] * 100
    t = pa.table({"s": pa.array(strs)})
    raw = write(t, compression="NONE", use_dictionary=False)
    got = decode.read_table(raw)
    assert got[0].to_pylist() == strs


def test_column_selection_and_multiple_row_groups():
    n = 4000
    t = pa.table({
        "a": pa.array(np.arange(n, dtype=np.int64)),
        "b": pa.array(np.arange(n, dtype=np.int32)),
        "c": pa.array(RNG.standard_normal(n)),
    })
    raw = write(t, compression="NONE", row_group_size=512)
    got = decode.read_table(raw, columns=["c", "a"])
    assert got.num_columns == 2
    np.testing.assert_array_equal(got[0].to_numpy(), t["c"].to_numpy())
    np.testing.assert_array_equal(got[1].to_numpy(), t["a"].to_numpy())


def test_rle_bitpacked_hybrid_unit():
    # 8 values of 3 bits bit-packed: spec example 0..7 → bytes 88 C6 FA
    out = decode.decode_rle_bitpacked_hybrid(
        bytes([0x03, 0x88, 0xC6, 0xFA]), 3, 8)
    np.testing.assert_array_equal(out, np.arange(8))
    # RLE run: header=(4<<1)|0, value 7
    out = decode.decode_rle_bitpacked_hybrid(bytes([0x08, 0x07]), 3, 4)
    np.testing.assert_array_equal(out, [7, 7, 7, 7])


# ---- q6 pipeline ----------------------------------------------------------

def make_lineitem(n=20000) -> tuple[bytes, pd.DataFrame]:
    epoch94 = 8766   # days 1970→1994-01-01
    df = pd.DataFrame({
        "l_quantity": RNG.integers(1, 51, n).astype(np.int64),
        "l_extendedprice": (RNG.random(n) * 100000).round(2),
        "l_discount": RNG.integers(0, 11, n).astype(np.float64) / 100.0,
        "l_shipdate": RNG.integers(epoch94 - 400, epoch94 + 800, n)
                      .astype(np.int32),
    })
    t = pa.table({
        "l_quantity": pa.array(df.l_quantity),
        "l_extendedprice": pa.array(df.l_extendedprice),
        "l_discount": pa.array(df.l_discount),
        "l_shipdate": pa.array(df.l_shipdate, type=pa.int32()),
    })
    return write(t, compression="NONE", row_group_size=4096), df


def test_q6_matches_pandas():
    raw, df = make_lineitem()
    lo, hi = 8766, 8766 + 365
    revenue, matched = q6.run(raw, lo, hi)
    m = ((df.l_shipdate >= lo) & (df.l_shipdate < hi)
         & (df.l_discount >= 0.05) & (df.l_discount <= 0.07)
         & (df.l_quantity < 24))
    expect = float((df.l_extendedprice[m] * df.l_discount[m]).sum())
    assert matched == int(m.sum())
    np.testing.assert_allclose(revenue, expect, rtol=1e-9)


# ---- snappy (pure-python decoder; pyarrow's bundled snappy is the writer
# oracle — the image has no python-snappy) ----------------------------------

def test_snappy_pages_roundtrip():
    n = 20000
    t = pa.table({
        "i64": pa.array(RNG.integers(-10**9, 10**9, n, dtype=np.int64)),
        "f64": pa.array(np.repeat(RNG.standard_normal(n // 100), 100)),
    })
    raw = write(t, compression="SNAPPY", use_dictionary=False)
    got = decode.read_table(raw)
    np.testing.assert_array_equal(got[0].to_numpy(), t["i64"].to_numpy())
    np.testing.assert_array_equal(got[1].to_numpy(), t["f64"].to_numpy())


def test_snappy_highly_compressible():
    """Runs/RLE-ish data exercises overlapping back-references."""
    n = 50000
    vals = np.zeros(n, dtype=np.int64)
    vals[::97] = np.arange(len(vals[::97]))
    t = pa.table({"v": pa.array(vals)})
    raw = write(t, compression="SNAPPY", use_dictionary=False)
    got = decode.read_table(raw)
    np.testing.assert_array_equal(got[0].to_numpy(), vals)


def test_snappy_decoder_rejects_corrupt():
    from spark_rapids_jni_tpu.parquet import snappy as sn
    with pytest.raises(sn.SnappyError):
        sn.decompress(b"\xff\xff\xff\xff\xff\xff")   # runaway varint
    with pytest.raises(sn.SnappyError):
        sn.decompress(b"\x10\x04abc")                # literal overrun
    # copy before start of output
    with pytest.raises(sn.SnappyError):
        sn.decompress(bytes([0x05, 0x00 | 0x00, ord("a"), 0x09, 0x10]))
