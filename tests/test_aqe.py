"""Adaptive query execution (SRJT_AQE): differential sweep.

Every adaptive decision — observed-cardinality join reorder, dense↔sorted
engine flip, skew-salted sub-joins — must be BIT-IDENTICAL to the static
plan and to the pandas oracle; with ``SRJT_AQE=0`` execution is
byte-for-byte the static path.  Replay consistency rides the same
discipline as capture/replay: decisions derive only from host-visible
row counts and ``syncs.scalar`` reads.
"""

import numpy as np
import pandas as pd
import pytest
import jax.numpy as jnp

import spark_rapids_jni_tpu as sr
from spark_rapids_jni_tpu.column import Column, Table, force_column
from spark_rapids_jni_tpu.ops import join_plan
from spark_rapids_jni_tpu.plan import adaptive, ir, lower
from spark_rapids_jni_tpu.plan import stats as plan_stats
from spark_rapids_jni_tpu.utils import metrics

N_DEV = 8


def _col(a):
    return Column.from_numpy(np.asarray(a))


def _rows(table):
    cols = [force_column(c).to_numpy().tolist() for c in table]
    return sorted(zip(*cols)) if cols else []


@pytest.fixture
def mx():
    metrics.set_enabled(True)
    metrics.reset()
    yield metrics
    metrics.set_enabled(None)


@pytest.fixture
def star():
    """Fact + big non-selective dim + small selective dim, plus an
    adversarially-ordered plan tree (big dim joins first)."""
    rng = np.random.default_rng(21)
    n = 6000
    tables = {
        "fact": Table([_col(rng.integers(0, 900, n).astype(np.int64)),
                       _col(rng.integers(0, 400, n).astype(np.int64)),
                       _col(rng.integers(1, 9, n).astype(np.int64))]),
        "dim_big": Table([_col(np.arange(900, dtype=np.int64)),
                          _col((np.arange(900) % 11).astype(np.int32))]),
        "dim_small": Table([_col(np.arange(24, dtype=np.int64)),
                            _col((np.arange(24) % 3).astype(np.int32))]),
    }
    schemas = {"fact": ["f_big_sk", "f_small_sk", "f_qty"],
               "dim_big": ["big_sk", "b_tag"],
               "dim_small": ["small_sk", "s_tag"]}
    tree = ir.FusedJoinAggregate(
        ir.Join(ir.Scan("fact"), ir.Scan("dim_big"),
                ("f_big_sk",), ("big_sk",)),
        ir.Scan("dim_small"), ("f_small_sk",), ("small_sk",),
        ("b_tag",), (("f_qty", "sum", "total"), ("f_qty", "count", "cnt")))
    return tables, schemas, tree


def _star_oracle(tables):
    f = pd.DataFrame({
        "f_big_sk": force_column(tables["fact"][0]).to_numpy(),
        "f_small_sk": force_column(tables["fact"][1]).to_numpy(),
        "f_qty": force_column(tables["fact"][2]).to_numpy()})
    big = pd.DataFrame({
        "big_sk": force_column(tables["dim_big"][0]).to_numpy(),
        "b_tag": force_column(tables["dim_big"][1]).to_numpy()})
    small = pd.DataFrame({
        "small_sk": force_column(tables["dim_small"][0]).to_numpy(),
        "s_tag": force_column(tables["dim_small"][1]).to_numpy()})
    j = f.merge(big, left_on="f_big_sk", right_on="big_sk")
    j = j.merge(small, left_on="f_small_sk", right_on="small_sk")
    g = j.groupby("b_tag")["f_qty"].agg(["sum", "count"]).reset_index()
    return sorted(zip(g["b_tag"].tolist(), g["sum"].tolist(),
                      g["count"].tolist()))


def test_aqe_off_is_static_path(star, mx, monkeypatch):
    monkeypatch.setenv("SRJT_AQE", "0")
    tables, schemas, tree = star
    got = lower.execute(tree, lower.TableCatalog(tables, schemas),
                        record_stats=False)
    assert _rows(got) == _star_oracle(tables)
    # no adaptive machinery ran
    snap = metrics.snapshot()["counters"]
    assert not any(k.startswith("plan.aqe") for k in snap), snap


def test_replan_adversarial_order_bit_identical(star, mx, monkeypatch):
    tables, schemas, tree = star
    monkeypatch.setenv("SRJT_AQE", "0")
    static = lower.execute(tree, lower.TableCatalog(tables, schemas),
                           record_stats=False)
    monkeypatch.setenv("SRJT_AQE", "1")
    report = adaptive.AdaptiveReport()
    got = adaptive.execute_adaptive(
        tree, lower.TableCatalog(tables, schemas), record_stats=False,
        report=report)
    assert _rows(got) == _rows(static) == _star_oracle(tables)
    assert metrics.counter_value("plan.aqe.replan.fired") >= 1
    assert "replan" in {d.kind for d in report.decisions()}
    assert "Adaptive execution" in report.render()


def test_execute_routes_on_knob(star, monkeypatch):
    tables, schemas, tree = star
    monkeypatch.setenv("SRJT_AQE", "1")
    via_route = lower.execute(tree, lower.TableCatalog(tables, schemas),
                              record_stats=False)
    assert _rows(via_route) == _star_oracle(tables)


@pytest.fixture
def sparse():
    """600 build keys scattered over [0, 15k): static prior says sorted,
    the observed probe cardinality (20k rows) says dense."""
    rng = np.random.default_rng(4)
    n = 20_000
    tables = {
        "fact": Table([_col(rng.integers(0, 15_000, n).astype(np.int64)),
                       _col(rng.integers(1, 9, n).astype(np.int64))]),
        "dim": Table([_col(rng.permutation(15_000)[:600].astype(np.int64)),
                      _col((np.arange(600) % 7).astype(np.int32))]),
    }
    schemas = {"fact": ["f_sk", "f_qty"], "dim": ["d_sk", "d_tag"]}
    tree = ir.FusedJoinAggregate(
        ir.Scan("fact"), ir.Scan("dim"), ("f_sk",), ("d_sk",),
        ("d_tag",), (("f_qty", "sum", "total"),))
    return tables, schemas, tree


def test_engine_flip_bit_identical(sparse, mx, monkeypatch):
    tables, schemas, tree = sparse
    monkeypatch.setenv("SRJT_AQE", "0")
    static = lower.execute(tree, lower.TableCatalog(tables, schemas),
                           record_stats=False)
    monkeypatch.setenv("SRJT_AQE", "1")
    report = adaptive.AdaptiveReport()
    got = adaptive.execute_adaptive(
        tree, lower.TableCatalog(tables, schemas), record_stats=False,
        report=report)
    assert _rows(got) == _rows(static)
    assert metrics.counter_value("plan.aqe.engine_flip.fired") >= 1
    assert metrics.counter_value("plan.aqe.engine_flip.dense") >= 1
    assert "engine_flip" in {d.kind for d in report.decisions()}


def test_ambient_force_engine_wins_over_probe(sparse, mx, monkeypatch):
    # scheduler degradation forces an engine ambient-wide; AQE must not
    # fight it (the probe is skipped entirely)
    tables, schemas, tree = sparse
    monkeypatch.setenv("SRJT_AQE", "1")
    report = adaptive.AdaptiveReport()
    with join_plan.force_engine("sorted"):
        got = adaptive.execute_adaptive(
            tree, lower.TableCatalog(tables, schemas), record_stats=False,
            report=report)
    monkeypatch.setenv("SRJT_AQE", "0")
    static = lower.execute(tree, lower.TableCatalog(tables, schemas),
                           record_stats=False)
    assert _rows(got) == _rows(static)
    assert "engine_flip" not in {d.kind for d in report.decisions()}
    assert metrics.counter_value("plan.aqe.engine_flip.fired") == 0


def test_regression_fires_flight_incident(sparse, mx, monkeypatch):
    tables, schemas, tree = sparse
    monkeypatch.setenv("SRJT_AQE", "1")
    # adversarial prior: the stats sidecar claims this stage yields 1 row,
    # the observed output is >2x that → regression incident
    plan_stats.GLOBAL.observe(ir.fingerprint(tree), 1)
    try:
        adaptive.execute_adaptive(
            tree, lower.TableCatalog(tables, schemas), record_stats=False)
        assert metrics.counter_value("plan.aqe.regression") >= 1
        assert metrics.counter_value("flight.incident.aqe_regression") >= 1
    finally:
        plan_stats.GLOBAL.clear()


def test_capture_replay_with_aqe(star, monkeypatch):
    from spark_rapids_jni_tpu.models.compiled import compile_query

    tables, schemas, tree = star
    monkeypatch.setenv("SRJT_AQE", "0")
    static = lower.execute(tree, lower.TableCatalog(tables, schemas),
                           record_stats=False)
    monkeypatch.setenv("SRJT_AQE", "1")
    qfn = lower.compile_plan(tree, schemas)
    assert getattr(qfn, "aqe_variant", "") == "aqe"
    cq = compile_query(qfn, tables)          # capture: decisions sync'd
    replayed = cq.run(tables)                # replay: same host branches
    assert _rows(replayed) == _rows(static)
    assert qfn.last_report is not None
    assert len(qfn.last_report.decisions()) >= 1


def test_plan_cache_variant_separates_aqe(star, monkeypatch):
    from spark_rapids_jni_tpu.exec.plan_cache import PlanCache

    tables, schemas, tree = star
    monkeypatch.setenv("SRJT_AQE", "0")
    static_qfn = lower.compile_plan(tree, schemas)
    monkeypatch.setenv("SRJT_AQE", "1")
    aqe_qfn = lower.compile_plan(tree, schemas)
    cache = PlanCache(cap=8)
    e1 = cache.get_or_compile("q", static_qfn, tables)
    e2 = cache.get_or_compile("q", aqe_qfn, tables)
    assert e1 is not e2, "AQE qfn adopted the static tape"
    # same variants hit their own entries
    assert cache.get_or_compile("q", static_qfn, tables) is e1
    assert cache.get_or_compile("q", aqe_qfn, tables) is e2


def test_stats_sidecar_roundtrip(tmp_path, mx):
    path = tmp_path / "stats.json"
    st = plan_stats.CardinalityStats(max_entries=8)
    st.observe("plan:a", 10)
    st.observe("plan:b", 20)
    assert st.save_sidecar(str(path))
    st2 = plan_stats.CardinalityStats(max_entries=8)
    assert st2.load_sidecar(str(path)) == 2
    assert dict(st2._rows) == {"plan:a": 10, "plan:b": 20}
    # live observations outrank persisted ones: a fresh observe for a
    # loaded fingerprint keeps the new value
    st2.observe("plan:a", 99)
    assert dict(st2._rows)["plan:a"] == 99
    # corrupt file → load returns 0, never raises
    path.write_text("{not json")
    assert plan_stats.CardinalityStats(max_entries=8).load_sidecar(
        str(path)) == 0


def test_sidecar_loaded_via_knob(tmp_path, monkeypatch):
    path = tmp_path / "stats.json"
    st = plan_stats.CardinalityStats(max_entries=8)
    st.observe("plan:seed", 7)
    assert st.save_sidecar(str(path))
    monkeypatch.setenv("SRJT_PLAN_STATS_PATH", str(path))
    monkeypatch.setattr(plan_stats, "_sidecar_loaded", False)
    before = len(plan_stats.GLOBAL)
    try:
        plan_stats.ensure_sidecar_loaded()
        assert len(plan_stats.GLOBAL) >= before
        assert dict(plan_stats.GLOBAL._rows).get("plan:seed") == 7
    finally:
        plan_stats.GLOBAL.clear()


@pytest.fixture(scope="module")
def mesh():
    from spark_rapids_jni_tpu.parallel import make_mesh
    return make_mesh(N_DEV, "data")


def test_salted_subjoin_zipf_bit_identical(mesh, mx, monkeypatch):
    from spark_rapids_jni_tpu.parallel import repartition_join as rj

    rng = np.random.default_rng(17)
    n, nb, G = 16_384, 512, 16
    fk = np.minimum(rng.zipf(2.0, n), nb) - 1        # Zipf-skewed keys
    fk = fk.astype(np.int64)
    fv = rng.integers(-30, 30, n).astype(np.int64)
    bk = np.arange(nb, dtype=np.int64)
    bg = rng.integers(0, G, nb).astype(np.int32)
    fvld = np.ones((n, 2), bool)
    fvld[:, 0] = rng.random(n) < 0.95                # some null keys
    args = (mesh, (sr.int64, sr.int64), (sr.int64, sr.int32),
            0, 0, 1, 1, G,
            (jnp.asarray(fk), jnp.asarray(fv)), jnp.asarray(fvld),
            (jnp.asarray(bk), jnp.asarray(bg)), jnp.ones((nb, 2), bool))
    monkeypatch.setenv("SRJT_AQE", "0")
    s1, c1, d1 = rj.repartition_join_agg_auto(*args, salt=1)
    monkeypatch.setenv("SRJT_AQE", "1")
    sA, cA, dA = rj.repartition_join_agg_auto(*args)
    s4, c4, d4 = rj.repartition_join_agg_auto(*args, salt=4)
    assert int(np.asarray(d1)) == int(np.asarray(dA)) == \
        int(np.asarray(d4)) == 0
    # pandas oracle
    f = pd.DataFrame({"k": fk, "v": fv})[fvld[:, 0]]
    b = pd.DataFrame({"k": bk, "g": bg})
    j = f.merge(b, on="k")
    o = j.groupby("g")["v"].agg(["sum", "count"]).reindex(
        range(G), fill_value=0)
    np.testing.assert_array_equal(np.asarray(s1), o["sum"].to_numpy())
    np.testing.assert_array_equal(np.asarray(c1), o["count"].to_numpy())
    # salted merges are exact: bit-identical to the unsalted join
    np.testing.assert_array_equal(np.asarray(sA), np.asarray(s1))
    np.testing.assert_array_equal(np.asarray(cA), np.asarray(c1))
    np.testing.assert_array_equal(np.asarray(s4), np.asarray(s1))
    np.testing.assert_array_equal(np.asarray(c4), np.asarray(c1))
    assert metrics.counter_value("plan.aqe.skew_split.fired") >= 1


def test_salt_validation(mesh):
    from spark_rapids_jni_tpu.parallel import repartition_join as rj

    n, nb = 64, 16
    args = (mesh, (sr.int64, sr.int64), (sr.int64, sr.int32),
            0, 0, 1, 1, 4,
            (jnp.zeros(n, jnp.int64), jnp.zeros(n, jnp.int64)),
            jnp.ones((n, 2), bool),
            (jnp.zeros(nb, jnp.int64), jnp.zeros(nb, jnp.int32)),
            jnp.ones((nb, 2), bool))
    with pytest.raises(ValueError, match="power of two"):
        rj.repartition_join_agg_auto(*args, salt=3)
