"""Device-bridge tests: bytes entering through the C/JNI surface are
transcoded by the DEVICE engine (VERDICT round-1 item 6; the reference's
JNI drives its device engine directly, RowConversionJni.cpp:24-45).

The pytest process hosts CPython, so ``srjt_device_available()`` is true
and ``srjt_to_rows_device`` round-trips through
``spark_rapids_jni_tpu.bridge`` → JAX engine → ``srjt_rows_import``.  The
host C++ engine output is the byte-exact oracle.
"""

import ctypes as C
import os

import numpy as np
import pytest

_LIB = os.path.join(os.path.dirname(__file__), "..",
                    "spark_rapids_jni_tpu", "native", "libsrjt.so")
if not os.path.exists(_LIB):
    pytest.skip("libsrjt.so not built", allow_module_level=True)

# the bridge module must resolve the SAME library instance
import spark_rapids_jni_tpu  # noqa: F401  (initializes jax/x64)

from spark_rapids_jni_tpu import native as _native

lib = _native.load()   # single shared binding site (native/__init__.py)
assert lib is not None

INT32, INT64, STRING = 3, 4, 24


def _np_ptr(a):
    return a.ctypes.data_as(C.c_void_p)


def _mixed_table(n=257):
    rng = np.random.default_rng(5)
    ints = rng.integers(-1000, 1000, n).astype(np.int32)
    longs = rng.integers(-10**12, 10**12, n).astype(np.int64)
    lens = rng.integers(0, 9, n).astype(np.int64)
    offs = np.zeros(n + 1, dtype=np.int32)
    np.cumsum(lens, out=offs[1:])
    chars = rng.integers(97, 123, int(offs[-1])).astype(np.uint8)
    valid = (rng.random(n) < 0.9).astype(np.uint8)
    h1 = lib.srjt_column_fixed(INT32, 0, n, _np_ptr(ints), _np_ptr(valid))
    h2 = lib.srjt_column_string(n, _np_ptr(offs), _np_ptr(chars), None)
    h3 = lib.srjt_column_fixed(INT64, 0, n, _np_ptr(longs), None)
    arr = (C.c_void_p * 3)(h1, h2, h3)
    t = lib.srjt_table(arr, 3)
    for h in (h1, h2, h3):
        lib.srjt_column_free(h)
    return t, (ints, offs, chars, valid, longs)


def _batch_bytes(rows):
    size = lib.srjt_rows_batch_size(rows, 0)
    return np.ctypeslib.as_array(lib.srjt_rows_batch_data(rows, 0),
                                 shape=(size,)).copy()


def test_device_available_in_python_process():
    assert lib.srjt_device_available() == 1


@pytest.mark.slow
def test_to_rows_device_matches_host_engine():
    t, _ = _mixed_table()
    host = lib.srjt_to_rows(t)
    dev = lib.srjt_to_rows_device(t)
    assert host and dev, "both engines must produce rows"
    assert lib.srjt_rows_num_batches(dev) == lib.srjt_rows_num_batches(host)
    np.testing.assert_array_equal(_batch_bytes(dev), _batch_bytes(host))
    lib.srjt_rows_free(host)
    lib.srjt_rows_free(dev)
    lib.srjt_table_free(t)


@pytest.mark.slow
def test_from_rows_device_roundtrip():
    t, (ints, offs, chars, valid, longs) = _mixed_table()
    rows = lib.srjt_to_rows_device(t)
    assert rows
    tids = np.asarray([INT32, STRING, INT64], dtype=np.int32)
    scales = np.zeros(3, dtype=np.int32)
    back = lib.srjt_from_rows_device(rows, _np_ptr(tids), _np_ptr(scales), 3)
    assert back
    assert lib.srjt_table_cols(back) == 3
    assert lib.srjt_table_rows(back) == len(ints)
    # int32 column payload must round-trip byte-exactly
    c0 = C.c_void_p(lib.srjt_table_column(back, 0))
    raw = np.ctypeslib.as_array(lib.srjt_column_data(c0),
                                shape=(lib.srjt_column_data_size(c0),))
    np.testing.assert_array_equal(raw.view(np.int32), ints)
    # string chars round-trip
    c1 = C.c_void_p(lib.srjt_table_column(back, 1))
    raw1 = np.ctypeslib.as_array(lib.srjt_column_data(c1),
                                 shape=(lib.srjt_column_data_size(c1),))
    np.testing.assert_array_equal(raw1, chars)
    lib.srjt_rows_free(rows)
    lib.srjt_table_free(t)
    lib.srjt_table_free(back)


@pytest.mark.slow
def test_srjt_device_kill_switch(monkeypatch):
    # SRJT_DEVICE=0 is the operator escape hatch forcing the host engine
    # (same convention as the SRJT_PALLAS dispatch toggle); getenv is read
    # per call, so flipping the env var takes effect immediately
    assert lib.srjt_device_available() == 1
    monkeypatch.setenv("SRJT_DEVICE", "0")
    assert lib.srjt_device_available() == 0
    t, _ = _mixed_table(16)
    assert not lib.srjt_to_rows_device(t)
    monkeypatch.delenv("SRJT_DEVICE")
    assert lib.srjt_device_available() == 1
    rows = lib.srjt_to_rows_device(t)
    assert rows
    lib.srjt_rows_free(rows)
    lib.srjt_table_free(t)
