"""Extended parquet decode coverage: INT96, FLBA decimals, DELTA encodings,
and single-level LIST columns — differential vs pyarrow-written files.

Closes VERDICT round-1 item 6 (decode.py:105,191,258,296,335 gaps).
"""

import decimal
import io

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_jni_tpu import types as T
from spark_rapids_jni_tpu.parquet.decode import (NestedDecodeUnsupported,
                                                 decode_delta_binary_packed,
                                                 read_table)


def write(table: pa.Table, **kw) -> bytes:
    buf = io.BytesIO()
    pq.write_table(table, buf, **kw)
    return buf.getvalue()


class TestDeltaBinaryPacked:
    def test_int64_roundtrip(self):
        rng = np.random.default_rng(0)
        vals = rng.integers(-10**12, 10**12, 5000)
        data = write(pa.table({"a": pa.array(vals, pa.int64())}),
                     use_dictionary=False,
                     column_encoding={"a": "DELTA_BINARY_PACKED"})
        t = read_table(data)
        np.testing.assert_array_equal(np.asarray(t[0].data), vals)

    def test_int32_monotonic(self):
        vals = np.arange(10000, dtype=np.int32) * 3 - 5000
        data = write(pa.table({"a": pa.array(vals, pa.int32())}),
                     use_dictionary=False,
                     column_encoding={"a": "DELTA_BINARY_PACKED"})
        t = read_table(data)
        assert t[0].dtype == T.int32
        np.testing.assert_array_equal(np.asarray(t[0].data), vals)

    def test_with_nulls(self):
        vals = [1, None, 3, None, -7] * 100
        data = write(pa.table({"a": pa.array(vals, pa.int64())}),
                     use_dictionary=False,
                     column_encoding={"a": "DELTA_BINARY_PACKED"})
        t = read_table(data)
        assert t[0].to_pylist() == vals

    def test_decoder_unit_tiny(self):
        # single value → no delta blocks at all
        data = write(pa.table({"a": pa.array([42], pa.int64())}),
                     use_dictionary=False,
                     column_encoding={"a": "DELTA_BINARY_PACKED"})
        assert read_table(data)[0].to_pylist() == [42]


class TestDeltaByteArray:
    def test_delta_length_byte_array(self):
        strs = [f"value_{i:05d}" for i in range(2000)] + ["", "x"]
        data = write(pa.table({"s": pa.array(strs)}), use_dictionary=False,
                     column_encoding={"s": "DELTA_LENGTH_BYTE_ARRAY"})
        assert read_table(data)[0].to_pylist() == strs

    def test_delta_byte_array_shared_prefixes(self):
        strs = sorted(f"prefix_{i % 7}_suffix_{i:04d}" for i in range(3000))
        data = write(pa.table({"s": pa.array(strs)}), use_dictionary=False,
                     column_encoding={"s": "DELTA_BYTE_ARRAY"})
        assert read_table(data)[0].to_pylist() == strs

    def test_delta_byte_array_nulls(self):
        strs = ["aa", None, "ab", "abc", None, "b"] * 50
        data = write(pa.table({"s": pa.array(strs)}), use_dictionary=False,
                     column_encoding={"s": "DELTA_BYTE_ARRAY"})
        assert read_table(data)[0].to_pylist() == strs


class TestInt96:
    def test_int96_timestamps(self):
        ts = pd.to_datetime(["1970-01-01 00:00:00",
                             "2020-02-29 23:59:59.123456",
                             "1969-12-31 12:00:00",
                             "2038-01-19 03:14:07"], format="mixed")
        data = write(pa.table({"ts": pa.array(ts)}),
                     use_deprecated_int96_timestamps=True)
        t = read_table(data)
        assert t[0].dtype == T.timestamp_ns
        want = ts.astype("datetime64[ns]").astype(np.int64)
        np.testing.assert_array_equal(np.asarray(t[0].data), want)


class TestDecimals:
    def test_flba_decimal128(self):
        vals = [decimal.Decimal("12345678901234567890.12"),
                decimal.Decimal("-0.01"), None,
                decimal.Decimal("99999999999999999999999999.99")]
        data = write(pa.table({"d": pa.array(vals, pa.decimal128(28, 2))}))
        t = read_table(data)
        assert t[0].dtype == T.decimal128(-2)
        want = [None if v is None else int(v.scaleb(2)) for v in vals]
        assert t[0].to_pylist() == want

    def test_flba_decimal64(self):
        vals = [decimal.Decimal("123456.789"), decimal.Decimal("-42.001"),
                None]
        data = write(pa.table({"d": pa.array(vals, pa.decimal128(15, 3))}))
        t = read_table(data)
        assert t[0].dtype == T.decimal64(-3)
        want = [None if v is None else int(v.scaleb(3)) for v in vals]
        assert t[0].to_pylist() == want

    def test_flba_decimal32(self):
        vals = [decimal.Decimal("1.23"), decimal.Decimal("-9.99")]
        data = write(pa.table({"d": pa.array(vals, pa.decimal128(7, 2))}))
        t = read_table(data)
        assert t[0].dtype == T.decimal32(-2)
        assert t[0].to_pylist() == [123, -999]

    def test_int32_int64_decimal(self):
        # pyarrow writes small decimals as int32/int64 when asked
        import pyarrow.parquet as _pq
        buf = io.BytesIO()
        tbl = pa.table({"d4": pa.array([decimal.Decimal("1.5")],
                                       pa.decimal128(4, 1)),
                        "d12": pa.array([decimal.Decimal("123.456")],
                                        pa.decimal128(12, 3))})
        _pq.write_table(tbl, buf, store_decimal_as_integer=True)
        t = read_table(buf.getvalue())
        assert t[0].dtype == T.decimal32(-1) and t[0].to_pylist() == [15]
        assert t[1].dtype == T.decimal64(-3) and t[1].to_pylist() == [123456]


class TestConvertedTypes:
    def test_date32(self):
        dates = pa.array([0, 365, -1, 19000], pa.date32())
        t = read_table(write(pa.table({"d": dates})))
        assert t[0].dtype == T.timestamp_days
        np.testing.assert_array_equal(np.asarray(t[0].data),
                                      [0, 365, -1, 19000])

    def test_timestamp_us_ms(self):
        us = pa.array([0, 10**15, -5], pa.timestamp("us"))
        ms = pa.array([0, 10**12, -5], pa.timestamp("ms"))
        t = read_table(write(pa.table({"us": us, "ms": ms})))
        assert t[0].dtype == T.timestamp_us
        assert t[1].dtype == T.timestamp_ms
        np.testing.assert_array_equal(np.asarray(t[0].data), [0, 10**15, -5])
        np.testing.assert_array_equal(np.asarray(t[1].data), [0, 10**12, -5])


class TestListColumns:
    def test_list_int(self):
        vals = [[1, 2], [], None, [5], None, [6, 7, 8]]
        data = write(pa.table({"l": pa.array(vals, pa.list_(pa.int32()))}))
        t = read_table(data)
        assert t[0].dtype.id == T.TypeId.LIST
        assert t[0].to_pylist() == vals

    def test_list_with_null_elements(self):
        vals = [[1, None, 3], None, [], [None]]
        data = write(pa.table({"l": pa.array(vals, pa.list_(pa.int64()))}))
        assert read_table(data)[0].to_pylist() == vals

    def test_list_strings(self):
        vals = [["ab", "c"], [], None, ["defg", None]]
        data = write(pa.table({"l": pa.array(vals, pa.list_(pa.string()))}))
        assert read_table(data)[0].to_pylist() == vals

    def test_list_many_rows_multi_group(self):
        rng = np.random.default_rng(1)
        vals = [None if rng.random() < 0.1 else
                list(rng.integers(0, 100, rng.integers(0, 6)).tolist())
                for _ in range(5000)]
        data = write(pa.table({"l": pa.array(vals, pa.list_(pa.int32()))}),
                     row_group_size=700)
        assert read_table(data)[0].to_pylist() == vals

    def test_list_of_list_rejected(self):
        vals = [[[1]], [[2, 3]]]
        data = write(pa.table(
            {"l": pa.array(vals, pa.list_(pa.list_(pa.int32())))}))
        with pytest.raises(NotImplementedError):
            read_table(data)

    def test_list_of_list_rejected_early_with_path(self):
        # the pre-decode schema walk names the offending column, so the
        # failure surfaces before any chunk decode (pruner/decoder parity)
        vals = [[[1]], [[2, 3]]]
        data = write(pa.table(
            {"deep": pa.array(vals, pa.list_(pa.list_(pa.int32())))}))
        with pytest.raises(NestedDecodeUnsupported, match="deep"):
            read_table(data)

    def test_map_rejected_early_with_path(self):
        data = write(pa.table(
            {"m": pa.array([[("k", 1)], [("j", 2)]],
                           pa.map_(pa.string(), pa.int64()))}))
        with pytest.raises(NestedDecodeUnsupported, match="m.*MAP"):
            read_table(data)

    def test_mixed_flat_and_list_with_selection(self):
        tbl = pa.table({
            "a": pa.array([1, 2, 3], pa.int64()),
            "l": pa.array([[1], [], [2, 3]], pa.list_(pa.int32())),
            "s": pa.array(["x", "y", "z"]),
        })
        t = read_table(write(tbl), columns=["s", "l"])
        assert t[0].to_pylist() == ["x", "y", "z"]
        assert t[1].to_pylist() == [[1], [], [2, 3]]


class TestDeltaUnit:
    def test_decode_delta_binary_packed_ref(self):
        # differential vs pyarrow over many shapes, via full files above;
        # here a hand-built stream: header(block=128, mini=4, count=3,
        # first=zigzag(5)) + one block
        import struct
        buf = bytearray()
        for v in (128, 4, 3, 10):     # 10 = zigzag(5)
            while v >= 0x80:
                buf.append((v & 0x7F) | 0x80)
                v >>= 7
            buf.append(v)
        buf.append(2)                  # min_delta = zigzag^-1(2) = 1
        buf += bytes([0, 0, 0, 0])     # all miniblock bitwidths 0
        vals, _ = decode_delta_binary_packed(bytes(buf))
        np.testing.assert_array_equal(vals, [5, 6, 7])


class TestByteArrayDecimal:
    def test_varlen_byte_array_decimal(self):
        # parquet-mr/Hive legacy writers store DECIMAL as variable-length
        # BYTE_ARRAY; craft one by rewriting the schema of an FLBA file is
        # complex, so build the decode path directly
        from spark_rapids_jni_tpu.parquet.decode import \
            _be_varlen_decimal_to_lanes
        vals = [12345, -1, 0, 2**100, -(2**90)]
        blobs = [v.to_bytes((v.bit_length() + 8) // 8 or 1, "big",
                            signed=True) for v in vals]
        chars = np.frombuffer(b"".join(blobs), np.uint8)
        lens = np.asarray([len(b) for b in blobs], np.int32)
        lanes = _be_varlen_decimal_to_lanes(chars, lens)
        from spark_rapids_jni_tpu.column import Column
        col = Column(T.decimal128(0), __import__("jax.numpy", fromlist=["x"]).asarray(lanes))
        assert col.to_pylist() == vals


class TestStructSelection:
    def test_struct_leaves_keep_dotted_paths(self):
        tbl = pa.table({"s": pa.array([{"a": 1, "b": "x"},
                                       {"a": 2, "b": "y"}],
                                      pa.struct([("a", pa.int64()),
                                                 ("b", pa.string())]))})
        t = read_table(write(tbl), columns=["s.b", "s.a"])
        assert t[0].to_pylist() == ["x", "y"]
        assert t[1].to_pylist() == [1, 2]
