"""Arrow interchange round-trips (cudf to_arrow/from_arrow analog)."""

import decimal

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_jni_tpu import types as T
from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.utils import arrow as A


class TestRoundTrip:
    def test_fixed_types(self):
        for typ, vals in [
            (pa.int32(), [1, None, -3]),
            (pa.int64(), [2**40, 0, None]),
            (pa.float64(), [1.5, None, -2.25]),
            (pa.uint8(), [0, 255, None]),
            (pa.bool_(), [True, None, False]),
            (pa.date32(), [0, 18321, None]),
            (pa.timestamp("us"), [0, 10**15, None]),
        ]:
            arr = pa.array(vals, typ)
            col = A.from_arrow(arr)
            back = A.to_arrow(col)
            assert back.to_pylist() == arr.to_pylist(), typ

    def test_strings_and_lists(self):
        arr = pa.array(["a", None, "bcd", ""])
        assert A.to_arrow(A.from_arrow(arr)).to_pylist() == arr.to_pylist()
        lst = pa.array([[1, 2], None, [], [5]], pa.list_(pa.int64()))
        col = A.from_arrow(lst)
        assert col.dtype.id == T.TypeId.LIST
        assert A.to_arrow(col).to_pylist() == lst.to_pylist()

    def test_decimals(self):
        small = pa.array([decimal.Decimal("1.25"), None], pa.decimal128(7, 2))
        col = A.from_arrow(small)
        assert col.dtype == T.decimal32(-2)
        assert A.to_arrow(col).to_pylist() == small.to_pylist()
        big = pa.array([decimal.Decimal("123456789012345678901.55"), None],
                       pa.decimal128(30, 2))
        col = A.from_arrow(big)
        assert col.dtype == T.decimal128(-2)
        assert A.to_arrow(col).to_pylist() == big.to_pylist()

    def test_table_roundtrip(self):
        tbl = pa.table({"a": pa.array([1, 2], pa.int32()),
                        "s": pa.array(["x", None]),
                        "d": pa.array([decimal.Decimal("9.99")] * 2,
                                      pa.decimal128(10, 2))})
        t = A.table_from_arrow(tbl)
        assert t.num_columns == 3 and t.num_rows == 2
        back = A.table_to_arrow(t, names=["a", "s", "d"])
        assert back.column("a").to_pylist() == [1, 2]
        assert back.column("s").to_pylist() == ["x", None]
        assert back.column("d").to_pylist() == tbl.column("d").to_pylist()

    def test_chunked_array(self):
        ch = pa.chunked_array([pa.array([1, 2], pa.int64()),
                               pa.array([3], pa.int64())])
        assert A.to_arrow(A.from_arrow(ch)).to_pylist() == [1, 2, 3]

    def test_unsupported_type_raises(self):
        with pytest.raises(NotImplementedError):
            A.from_arrow(pa.array([{"a": 1}], pa.struct([("a", pa.int64())])))


class TestReviewRegressions:
    def test_38_digit_decimal_exact(self):
        v = decimal.Decimal("123456789012345678901234567890.12")
        arr = pa.array([v, None], pa.decimal128(38, 2))
        col = A.from_arrow(arr)
        assert col.to_pylist()[0] == int(
            decimal.Decimal("12345678901234567890123456789012"))
        assert A.to_arrow(col).to_pylist() == [v, None]

    def test_nullable_int64_above_2_53(self):
        arr = pa.array([2**62 + 1, None], pa.int64())
        col = A.from_arrow(arr)
        assert col.to_pylist() == [2**62 + 1, None]

    def test_decimal64_19_digit_unscaled(self):
        col = Column.from_numpy(
            np.asarray([9223372036854775807], np.int64), T.decimal64(-2))
        out = A.to_arrow(col)
        assert out.to_pylist() == [decimal.Decimal("92233720368547758.07")]

    def test_duplicate_names_preserved(self):
        t = Table([Column.from_numpy(np.asarray([1], np.int32)),
                   Column.from_numpy(np.asarray([2], np.int32))])
        out = A.table_to_arrow(t, names=["k", "k"])
        assert out.num_columns == 2
