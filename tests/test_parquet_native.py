"""Differential tests: native C++ footer engine vs the Python engine.

Both engines implement the same reference semantics (NativeParquetJni.cpp);
their serialized outputs must be byte-identical on every scenario.
"""

import io

import pyarrow.parquet as pq
import pytest

from spark_rapids_jni_tpu.parquet import (
    StructElement, ValueElement, ListElement, MapElement, read_and_filter)
from spark_rapids_jni_tpu.parquet import footer_native
from spark_rapids_jni_tpu.parquet.footer import extract_footer_bytes

from test_parquet_footer import simple_file, nested_file

pytestmark = pytest.mark.skipif(
    not footer_native.available(), reason="native engine not built")


SCENARIOS = [
    ("subset", simple_file,
     StructElement("root", ValueElement("a"), ValueElement("c")), 0, -1, False),
    ("case_fold", simple_file,
     StructElement("root", ValueElement("b"), ValueElement("D")), 0, -1, True),
    ("missing_col", simple_file,
     StructElement("root", ValueElement("a"), ValueElement("zz")), 0, -1, False),
    ("nested", nested_file,
     StructElement("root", StructElement("s", ValueElement("x")),
                   ValueElement("id")), 0, -1, False),
    ("list_map", nested_file,
     StructElement("root", ListElement("l", ValueElement("element")),
                   MapElement("m", ValueElement("key"), ValueElement("value"))),
     0, -1, False),
]


@pytest.mark.parametrize("name,mkfile,schema,off,length,ic",
                         SCENARIOS, ids=[s[0] for s in SCENARIOS])
def test_native_matches_python(name, mkfile, schema, off, length, ic):
    raw = extract_footer_bytes(mkfile())
    py = read_and_filter(raw, off, length, schema, ic)
    with footer_native.read_and_filter(raw, off, length, schema, ic) as nat:
        assert nat.num_rows == py.num_rows
        assert nat.num_columns == py.num_columns
        assert nat.serialize_thrift_file() == py.serialize_thrift_file()


def test_native_split_filtering_matches_python():
    raw_file = simple_file(n=10000, row_group_size=1000)
    raw = extract_footer_bytes(raw_file)
    schema = StructElement("root", ValueElement("a"))
    half = len(raw_file) // 2
    for off, length in [(0, half), (half, len(raw_file) - half),
                        (0, len(raw_file))]:
        py = read_and_filter(raw, off, length, schema)
        with footer_native.read_and_filter(raw, off, length, schema) as nat:
            assert nat.num_rows == py.num_rows
            assert nat.serialize_thrift_file() == py.serialize_thrift_file()


def test_native_output_reparses_with_pyarrow():
    raw = extract_footer_bytes(simple_file())
    schema = StructElement("root", ValueElement("a"), ValueElement("c"))
    with footer_native.read_and_filter(raw, 0, -1, schema) as nat:
        md = pq.read_metadata(io.BytesIO(nat.serialize_thrift_file()))
    assert md.schema.names == ["a", "c"]


def test_native_error_on_garbage():
    schema = StructElement("root", ValueElement("a"))
    with pytest.raises(ValueError, match="footer read/filter failed"):
        footer_native.read_and_filter(b"\xff\xfe\xfd" * 100, 0, -1, schema)


def test_native_use_after_close_raises():
    raw = extract_footer_bytes(simple_file())
    schema = StructElement("root", ValueElement("a"))
    nat = footer_native.read_and_filter(raw, 0, -1, schema)
    nat.close()
    with pytest.raises(ValueError):
        _ = nat.num_rows


def test_native_uppercase_expected_names_fold():
    raw = extract_footer_bytes(simple_file())
    schema = StructElement("root", ValueElement("A"), ValueElement("D"))
    py = read_and_filter(raw, 0, -1, schema, ignore_case=True)
    with footer_native.read_and_filter(raw, 0, -1, schema, True) as nat:
        assert nat.num_columns == 2
        assert nat.serialize_thrift_file() == py.serialize_thrift_file()


def test_native_malformed_rowgroup_error_not_crash():
    from spark_rapids_jni_tpu.parquet.thrift import (Struct, Field, ListValue,
                                                     TType, serialize_struct)
    root = Struct([Field(4, TType.BINARY, b"root"), Field(5, TType.I32, 1)])
    leaf = Struct([Field(1, TType.I32, 1), Field(4, TType.BINARY, b"a")])
    bad_group = Struct([Field(3, TType.I64, 7)])   # num_rows but NO columns
    meta = Struct([
        Field(2, TType.LIST, ListValue(TType.STRUCT, [root, leaf])),
        Field(4, TType.LIST, ListValue(TType.STRUCT, [bad_group]))])
    blob = serialize_struct(meta)
    schema = StructElement("root", ValueElement("a"))
    with pytest.raises(ValueError, match="malformed footer"):
        footer_native.read_and_filter(blob, 0, 100, schema)
