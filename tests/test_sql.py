"""SQL front-end tests: grammar round-trips, TPC-DS SQL differentials
(bit-identical through the exec scheduler, fingerprint-shared with
hand-built trees), binder errors with caret positions, submit_sql
parity, and plan-cache/SQL-memo dedupe counters."""

import numpy as np
import pytest

from benchmarks import tpcds_data
from spark_rapids_jni_tpu import sql as sql_fe
from spark_rapids_jni_tpu.column import force_column
from spark_rapids_jni_tpu.exec.scheduler import QueryScheduler
from spark_rapids_jni_tpu.models import tpcds
from spark_rapids_jni_tpu.models import tpcds_sql as TS
from spark_rapids_jni_tpu.plan import ir, lower, rules
from spark_rapids_jni_tpu.sql import SqlError, parse, to_sql
from spark_rapids_jni_tpu.utils import flight, metrics

SCHEMAS = TS.TABLE_SCHEMAS


@pytest.fixture(autouse=True)
def _metrics_on():
    metrics.set_enabled(True)
    metrics.reset()
    sql_fe.clear_cache()
    yield
    metrics.reset()
    metrics.set_enabled(None)


@pytest.fixture(scope="module")
def tables():
    # same parameters as test_exec_runtime's dataset: generate() is
    # memoized, so the byte blobs (and their decode) are shared
    files = tpcds_data.generate(n_sales=20_000, n_items=300, seed=11)
    return tpcds.load_tables(files)


@pytest.fixture(scope="module")
def sched():
    s = QueryScheduler(workers=2)
    yield s
    s.shutdown()


def _assert_tables_identical(a, b):
    assert a.num_columns == b.num_columns
    assert a.num_rows == b.num_rows
    for i in range(a.num_columns):
        ca, cb = force_column(a[i]), force_column(b[i])
        assert np.array_equal(np.asarray(ca.data), np.asarray(cb.data),
                              equal_nan=True), f"column {i} data"
        va = None if ca.validity is None else np.asarray(ca.validity)
        vb = None if cb.validity is None else np.asarray(cb.validity)
        assert (va is None) == (vb is None), f"column {i} validity kind"
        assert va is None or np.array_equal(va, vb), f"column {i} validity"


# --- grammar round-trips -----------------------------------------------------

@pytest.mark.parametrize("name", TS.QUERY_NAMES)
def test_roundtrip_fingerprint_stable(name):
    """parse → render → parse must bind to the same tree: the rendered
    SQL is a faithful spelling of the original."""
    params = TS.PARAMS.get(name, {})
    q1 = parse(TS.SQL[name])
    rendered = to_sql(q1)
    q2 = parse(rendered)
    t1 = sql_fe.bind(q1, SCHEMAS, params, TS.SQL[name])
    t2 = sql_fe.bind(q2, SCHEMAS, params, rendered)
    assert ir.fingerprint(t1) == ir.fingerprint(t2)
    # and the renderer is idempotent
    assert to_sql(q2) == rendered


@pytest.mark.parametrize("name", TS.QUERY_NAMES)
def test_optimized_fingerprint_matches_hand_tree(name):
    """The SQL-born optimized tree IS the hand-built optimized tree —
    one structural fingerprint, hence one plan-cache/AOT identity."""
    params = TS.PARAMS.get(name, {})
    sql_tree = sql_fe.sql_to_plan(TS.SQL[name], SCHEMAS, params)
    hand = rules.optimize(TS.hand_tree(name), SCHEMAS).tree
    assert ir.fingerprint(sql_tree) == ir.fingerprint(hand)


# --- TPC-DS SQL differentials through the exec scheduler ---------------------

# the 8 heaviest JIT compiles ride in the slow lane; the 20 below the
# line keep the tier-1 differential floor (>=20 queries) inside the
# suite's time budget — the full sweep still runs without `-m 'not slow'`
_SLOW_DIFF = {"q_isin_states", "q19", "q7", "q62_range", "q52",
              "q_store_counts", "q67_rank", "q3"}


@pytest.mark.parametrize(
    "name", [pytest.param(n, marks=pytest.mark.slow) if n in _SLOW_DIFF
             else n for n in TS.QUERY_NAMES])
def test_tpcds_sql_differential(name, tables, sched):
    """submit_sql result is bit-identical to the hand-built plan tree
    executed through the same scheduler."""
    params = TS.PARAMS.get(name, {})
    hand = rules.optimize(TS.hand_tree(name), SCHEMAS).tree
    hqfn = lower.compile_plan(hand, SCHEMAS)
    r_hand = sched.run(ir.fingerprint(hand), hqfn, tables)
    r_sql = sched.submit_sql(TS.SQL[name], tables, schemas=SCHEMAS,
                             params=params).result()
    _assert_tables_identical(r_hand, r_sql)


def test_submit_sql_plan_cache_dedupe(tables, sched):
    """A SQL submission reuses the plan-cache entry the equivalent
    hand-built tree compiled — cache HIT, no second compile."""
    hand = rules.optimize(TS.hand_tree("q55"), SCHEMAS).tree
    hqfn = lower.compile_plan(hand, SCHEMAS)
    sched.run(ir.fingerprint(hand), hqfn, tables)   # warm the entry
    h0 = metrics.counter_value("exec.plan_cache.hit")
    m0 = metrics.counter_value("exec.plan_cache.miss")
    out = sched.submit_sql(TS.SQL["q55"], tables, schemas=SCHEMAS,
                           params=TS.PARAMS["q55"]).result()
    assert out.num_rows >= 0
    assert metrics.counter_value("exec.plan_cache.hit") == h0 + 1
    assert metrics.counter_value("exec.plan_cache.miss") == m0


def test_sql_memo_warm_hit():
    """Second sql_to_plan of identical (text, params, schemas) returns
    the SAME tree object with a cache-hit counter tick — parse cost is
    amortized to zero on warm repeats."""
    a = sql_fe.sql_to_plan(TS.SQL["q3"], SCHEMAS, TS.PARAMS["q3"])
    b = sql_fe.sql_to_plan(TS.SQL["q3"], SCHEMAS, TS.PARAMS["q3"])
    assert a is b
    assert metrics.counter_value("sql.cache.hit") == 1
    assert metrics.counter_value("sql.cache.miss") == 1
    # different params → different plan, no false sharing
    c = sql_fe.sql_to_plan(TS.SQL["q3"], SCHEMAS,
                           {"manufact_id": 1, "moy": 12})
    assert c is not a
    assert metrics.counter_value("sql.cache.miss") == 2


def test_submit_sql_params_change_fingerprint(tables, sched):
    p1 = dict(TS.PARAMS["q55"])
    p2 = {"manager_id": p1["manager_id"] + 1}
    t1 = sql_fe.sql_to_plan(TS.SQL["q55"], SCHEMAS, p1)
    t2 = sql_fe.sql_to_plan(TS.SQL["q55"], SCHEMAS, p2)
    assert ir.fingerprint(t1) != ir.fingerprint(t2)


# --- errors: typed SqlError with caret ---------------------------------------

def _sql_error(text, schemas=None, params=None):
    with pytest.raises(SqlError) as ei:
        sql_fe.sql_to_plan(text, SCHEMAS if schemas is None else schemas,
                           params)
    return ei.value


def test_unknown_column_caret():
    e = _sql_error("SELECT nope FROM item")
    assert "unknown column 'nope'" in e.message
    assert (e.line, e.col) == (1, 8)        # caret under 'nope'
    src, caret = str(e).splitlines()[-2:]
    assert src.endswith("SELECT nope FROM item")
    # the rendered caret sits under source column 8 (4-space indent)
    assert caret.index("^") == 4 + e.col - 1


def test_unknown_table_caret():
    e = _sql_error("SELECT i_brand_id FROM nosuch")
    assert "unknown table 'nosuch'" in e.message
    assert (e.line, e.col) == (1, 24)


def test_binder_error_caret_multiline():
    text = ("SELECT i_brand_id, SUM(kaboom) AS s\n"
            "FROM item\n"
            "GROUP BY i_brand_id")
    e = _sql_error(text)
    assert "unknown column 'kaboom'" in e.message
    assert e.line == 1
    assert e.col == text.splitlines()[0].index("kaboom") + 1


def test_duplicate_join_names_rejected():
    schemas = {"a": ["x", "k"], "b": ["x", "j"]}
    e = _sql_error("SELECT x FROM a JOIN b ON k = j", schemas=schemas)
    assert "share column names ['x']" in e.message


def test_ambiguous_join_key_error():
    schemas = {"a": ["x", "k"], "b": ["x", "j"]}
    e = _sql_error("SELECT k FROM a JOIN b ON x = j", schemas=schemas)
    assert "ambiguous join key 'x'" in e.message
    assert (e.line, e.col) == (1, 27)       # caret under the ON's 'x'


def test_unbound_parameter_error():
    e = _sql_error("SELECT i_brand_id, SUM(i_item_sk) AS s FROM item "
                   "WHERE i_manager_id = :m GROUP BY i_brand_id")
    assert "unbound parameter :m" in e.message


def test_rename_outside_union_rejected():
    e = _sql_error("SELECT i_brand_id AS b FROM item")
    assert "UNION ALL" in e.message


def test_aggregate_without_group_by_rejected():
    e = _sql_error("SELECT SUM(i_item_sk) AS s FROM item")
    assert "GROUP BY" in e.message


def test_count_distinct_must_be_sole_aggregate():
    e = _sql_error("SELECT i_brand_id, COUNT(DISTINCT i_item_sk) AS a, "
                   "SUM(i_item_sk) AS b FROM item GROUP BY i_brand_id")
    assert "only aggregate" in e.message


def test_order_by_outside_select_rejected():
    e = _sql_error("SELECT i_brand_id, SUM(i_item_sk) AS s FROM item "
                   "GROUP BY i_brand_id ORDER BY i_category_id")
    assert "ORDER BY" in e.message


def test_union_arity_mismatch():
    e = _sql_error(
        "SELECT i_brand_id, SUM(i_item_sk) AS s FROM item "
        "GROUP BY i_brand_id "
        "UNION ALL "
        "SELECT i_brand_id FROM item")
    assert "UNION ALL arm" in e.message


def test_unterminated_string_caret():
    e = _sql_error("SELECT s_state FROM store WHERE s_state IN ('TN")
    assert "unterminated string" in e.message
    assert e.col == 45                      # caret under the opening quote


def test_trailing_garbage_rejected():
    with pytest.raises(SqlError):
        parse("SELECT i_brand_id FROM item extra garbage here")


def test_sql_parse_error_flight_incident():
    flight.set_enabled(True)
    try:
        base = metrics.counter_value("flight.incident.sql_parse_error")
        with pytest.raises(SqlError):
            sql_fe.sql_to_plan("SELECT nope FROM item", SCHEMAS)
        assert metrics.counter_value(
            "flight.incident.sql_parse_error") == base + 1
        evs = [e for e in flight.events(last=20)
               if e["kind"] == "incident:sql_parse_error"]
        assert evs, "incident event missing from the flight ring"
        assert evs[-1]["line"] == 1 and evs[-1]["col"] == 8
    finally:
        flight.set_enabled(None)


def test_max_len_guard(monkeypatch):
    monkeypatch.setenv("SRJT_SQL_MAX_LEN", "16")
    with pytest.raises(SqlError) as ei:
        sql_fe.sql_to_plan("SELECT i_brand_id FROM item", SCHEMAS)
    assert "SRJT_SQL_MAX_LEN" in ei.value.message


# --- grammar corners not exercised by the corpus -----------------------------

def test_or_predicate_and_qualified_refs(tables):
    text = ("SELECT i.i_brand_id, SUM(s.ss_ext_sales_price) AS total "
            "FROM store_sales s JOIN item i ON s.ss_item_sk = i.i_item_sk "
            "WHERE i.i_manager_id = 1 OR i.i_manager_id = 2 "
            "GROUP BY i.i_brand_id ORDER BY i.i_brand_id")
    tree = sql_fe.sql_to_plan(text, SCHEMAS)
    hand = rules.optimize(ir.Sort(ir.Aggregate(
        ir.Filter(ir.Join(ir.Scan("store_sales"), ir.Scan("item"),
                          ("ss_item_sk",), ("i_item_sk",)),
                  ir.Or((ir.Cmp("==", ir.Col("i_manager_id"), ir.Lit(1)),
                         ir.Cmp("==", ir.Col("i_manager_id"), ir.Lit(2))))),
        ("i_brand_id",), (("ss_ext_sales_price", "sum", "total"),)),
        ("i_brand_id",)), SCHEMAS).tree
    assert ir.fingerprint(tree) == ir.fingerprint(hand)
    qfn = lower.compile_plan(tree, SCHEMAS)
    hfn = lower.compile_plan(hand, SCHEMAS)
    _assert_tables_identical(qfn(tables), hfn(tables))


def test_lead_and_dense_rank_windows(tables):
    text = ("SELECT d_year, d_moy, SUM(ss_ext_sales_price) AS m_total, "
            "LEAD(m_total) OVER (PARTITION BY d_year ORDER BY d_moy) "
            "AS nxt, "
            "DENSE_RANK() OVER (PARTITION BY d_year ORDER BY m_total DESC) "
            "AS dr "
            "FROM store_sales "
            "JOIN date_dim ON ss_sold_date_sk = d_date_sk "
            "GROUP BY d_year, d_moy")
    tree = sql_fe.sql_to_plan(text, SCHEMAS)
    agg = ir.Aggregate(
        ir.Join(ir.Scan("store_sales"), ir.Scan("date_dim"),
                ("ss_sold_date_sk",), ("d_date_sk",)),
        ("d_year", "d_moy"), (("ss_ext_sales_price", "sum", "m_total"),))
    w1 = ir.Window(agg, "lead", ("d_year",), ("d_moy",), "nxt",
                   value="m_total")
    w2 = ir.Window(w1, "dense_rank", ("d_year",), ("m_total",), "dr",
                   ascending=(False,))
    hand = rules.optimize(w2, SCHEMAS).tree
    assert ir.fingerprint(tree) == ir.fingerprint(hand)
    _assert_tables_identical(lower.compile_plan(tree, SCHEMAS)(tables),
                             lower.compile_plan(hand, SCHEMAS)(tables))


def test_comments_and_semicolon():
    text = ("-- top brands\n"
            "SELECT i_brand_id, SUM(i_item_sk) AS s  -- trailing note\n"
            "FROM item GROUP BY i_brand_id;")
    tree = sql_fe.sql_to_plan(text, SCHEMAS)
    assert isinstance(tree, ir.Plan)
