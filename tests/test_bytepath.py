"""Byte-path differential suite (round 6).

Every raw-speed path the staging campaign added must be BIT-IDENTICAL to
the eager path it replaced, across the encodings the scan tier handles:

* slab-coalesced (and pipelined) staging vs eager per-buffer uploads,
  including dictionary-encoded and null-heavy columns;
* the Pallas kernels vs their lax fallbacks (interpret mode — CPU CI
  gates parity; chip wins are measured, not assumed);
* the fused scan→filter vs scan-then-``apply_boolean_mask``, at both the
  scanner and the planner tier;
* buffer donation forced on, under ``SRJT_SANITIZE=strict``.
"""

import io

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import pyarrow as pa
import pyarrow.parquet as pq

from spark_rapids_jni_tpu.parquet import device_scan
from spark_rapids_jni_tpu.utils import flight

RNG = np.random.default_rng(29)
N = 6000


def _write(t: pa.Table, **kw) -> bytes:
    buf = io.BytesIO()
    pq.write_table(t, buf, **kw)
    return buf.getvalue()


@pytest.fixture(scope="module")
def raw() -> bytes:
    nn = RNG.integers(0, 1000, N).astype(np.int64)
    t = pa.table({
        "a": pa.array(RNG.integers(0, 1000, N).astype(np.int32)),
        "f": pa.array(RNG.standard_normal(N)),
        "low": pa.array(RNG.integers(0, 50, N).astype(np.int64)),
        "d": pa.array([f"val{v}" for v in RNG.integers(0, 30, N)]),
        "s": pa.array([f"s{v}" for v in RNG.integers(0, 2000, N)]),
        "nn": pa.array([None if m else int(v) for v, m in
                        zip(nn, RNG.random(N) < 0.4)], pa.int64()),
    })
    return _write(t, compression="NONE", row_group_size=1500)


def _assert_tables_identical(a, b):
    assert a.num_columns == b.num_columns
    for ca, cb in zip(a.columns, b.columns):
        # paths may differ in wrapper class (Lazy/Dict) but never in
        # dtype or bytes
        assert ca.dtype == cb.dtype
        np.testing.assert_array_equal(np.asarray(ca.data),
                                      np.asarray(cb.data))
        if ca.offsets is not None:
            np.testing.assert_array_equal(np.asarray(ca.offsets),
                                          np.asarray(cb.offsets))
        np.testing.assert_array_equal(np.asarray(ca.validity_or_true()),
                                      np.asarray(cb.validity_or_true()))


def _scan(raw_bytes, monkeypatch, env, **kw):
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    try:
        return device_scan.scan_table(raw_bytes, **kw)
    finally:
        for k in env:
            monkeypatch.delenv(k, raising=False)


@pytest.fixture(scope="module")
def eager(raw):
    """The eager-path reference scan, shared across comparisons (these
    knobs are host-side — no jit cache interaction, safe to reuse)."""
    import os
    os.environ["SRJT_STAGE_SLABS"] = "0"
    os.environ["SRJT_FUSED_FILTER"] = "0"
    try:
        return device_scan.scan_table(raw)
    finally:
        del os.environ["SRJT_STAGE_SLABS"], os.environ["SRJT_FUSED_FILTER"]


# --- staged vs eager ---------------------------------------------------------


@pytest.mark.parametrize("pipeline", ["0", "1"])
def test_staged_scan_bit_identical(raw, eager, monkeypatch, pipeline):
    staged = _scan(raw, monkeypatch, {"SRJT_STAGE_SLABS": "1",
                                      "SRJT_STAGE_PIPELINE": pipeline})
    _assert_tables_identical(eager, staged)


def test_staged_scan_coalesces_and_overlaps(raw, monkeypatch):
    was = flight.enabled()
    flight.set_enabled(True)
    flight.reset()
    try:
        _scan(raw, monkeypatch, {"SRJT_STAGE_SLABS": "1",
                                 "SRJT_STAGE_PIPELINE": "1"})
        evs = flight.events()
    finally:
        flight.set_enabled(was)
    flushes = [e for e in evs if e["kind"] == "parquet.stage.flush"]
    assert flushes and sum(e["slabs"] for e in flushes) >= 1
    overlap = [e for e in evs if e["kind"] == "parquet.stage.overlap"]
    assert overlap and overlap[-1]["columns"] > 1


def test_staged_tiny_slab_cap_still_identical(raw, eager, monkeypatch):
    # a 4 KiB cap forces many waves/slabs — split boundaries must not
    # change a single byte
    staged = _scan(raw, monkeypatch, {"SRJT_STAGE_SLABS": "1",
                                      "SRJT_STAGE_SLAB_BYTES": "4096"})
    _assert_tables_identical(eager, staged)


# --- pallas kernels (interpret) ---------------------------------------------


def test_pallas_u8_to_u32_parity(monkeypatch):
    from spark_rapids_jni_tpu.rowconv import xpallas
    monkeypatch.setenv("SRJT_PALLAS_TRANSPOSE", "interpret")
    flat = jnp.asarray(RNG.integers(0, 256, 4 * 512 * 3, dtype=np.int64)
                       .astype(np.uint8))
    out = xpallas.try_u8_to_u32(flat)
    assert out is not None
    ref = np.frombuffer(np.asarray(flat).tobytes(), np.uint32)
    np.testing.assert_array_equal(np.asarray(out), ref)


def test_pallas_gather_rows_parity(monkeypatch):
    from spark_rapids_jni_tpu.rowconv import xpallas
    monkeypatch.setenv("SRJT_PALLAS_DICT_GATHER", "interpret")
    mat = jnp.asarray(RNG.integers(0, 2**32, (77, 19), dtype=np.int64)
                      .astype(np.uint32))
    idx = jnp.asarray(RNG.integers(0, 77, 999).astype(np.int32))
    out = xpallas.try_gather_rows(mat, idx)
    assert out is not None
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(mat)[np.asarray(idx)])


def test_pallas_extract_rows_parity(monkeypatch):
    from spark_rapids_jni_tpu.rowconv import xpallas
    monkeypatch.setenv("SRJT_PALLAS_EXTRACT", "interpret")
    rows, M = 50, 48
    lens = RNG.integers(1, 40, rows)
    offs = np.zeros(rows + 1, np.int64)
    offs[1:] = np.cumsum(lens)
    payload = RNG.integers(0, 256, int(offs[-1]), dtype=np.int64) \
        .astype(np.uint8)
    out = xpallas.try_extract_rows(jnp.asarray(payload), offs, M)
    assert out is not None
    got = np.asarray(out)
    for j in range(rows):
        ln = min(int(lens[j]), M)
        np.testing.assert_array_equal(got[j, :ln],
                                      payload[offs[j]:offs[j] + ln])


def test_pallas_pack_windows_parity(monkeypatch):
    from spark_rapids_jni_tpu.rowconv import xpack, xpallas
    n, Mw = 512, 40
    dense = jnp.asarray(RNG.integers(0, 2**32, (n, Mw), dtype=np.int64)
                        .astype(np.uint32))
    # rows are 8-byte aligned (the layout contract): even word sizes;
    # P must cover every row starting inside one 128-word window
    rs = 2 * RNG.integers(8, Mw // 2 + 1, n)
    dst = np.concatenate([[0], np.cumsum(rs)]).astype(np.int32)
    dst_w = jnp.asarray(dst)
    total_w = int(dst[-1])
    nwin = -(-total_w // xpack.WIN_W)
    P = int(np.bincount(dst[:-1] // xpack.WIN_W,
                        minlength=nwin).max()) + 1
    lax_out = np.asarray(xpack.pack_windows(dense, dst_w, total_w, P, nwin))
    monkeypatch.setenv("SRJT_PALLAS_PACKWIN", "interpret")
    out = xpallas.try_pack_windows(dense, dst_w, total_w, P, nwin)
    assert out is not None
    np.testing.assert_array_equal(np.asarray(out), lax_out)


@pytest.mark.slow
def test_pallas_interpret_scan_bit_identical(raw, monkeypatch):
    """The whole scan with every kernel knob in interpret mode — the
    in-trace dispatch sites (dict gather, u8→u32) against the lax scan."""
    from spark_rapids_jni_tpu.rowconv import xpallas
    base = _scan(raw, monkeypatch, {"SRJT_DICT_STRINGS": "0"})
    jax.clear_caches()
    before = dict(xpallas._counts)
    knobs_env = {"SRJT_PALLAS_TRANSPOSE": "interpret",
                 "SRJT_PALLAS_DICT_GATHER": "interpret",
                 "SRJT_PALLAS_EXTRACT": "interpret",
                 "SRJT_PALLAS_PACKWIN": "interpret",
                 "SRJT_DICT_STRINGS": "0"}
    try:
        pall = _scan(raw, monkeypatch, knobs_env)
    finally:
        jax.clear_caches()     # drop kernel-mode traces for later tests
    assert xpallas._counts["hits"] > before.get("hits", 0)
    _assert_tables_identical(base, pall)


# --- fused scan→filter -------------------------------------------------------


@pytest.fixture(scope="module")
def pdf(raw):
    return pq.read_table(io.BytesIO(raw)).to_pandas()


def _ref_filtered(t, df, conds):
    """Reference: the shared unfiltered scan + the planner's own mask
    semantics (nulls fail every conjunct)."""
    from spark_rapids_jni_tpu.ops.filter import apply_boolean_mask
    keep = np.ones(len(df), bool)
    for cname, op, val in conds:
        col = df[cname]
        v = val.decode() if isinstance(val, bytes) else val
        m = {"eq": col == v, "lt": col < v, "le": col <= v,
             "gt": col > v, "ge": col >= v}[op]
        keep &= np.asarray(m.fillna(False)) & ~np.asarray(col.isna())
    return apply_boolean_mask(t, jnp.asarray(keep)), int(keep.sum())


# each distinct kept-row count retraces the decode program, so the
# per-case cost is real compile time: keep one case per predicate
# category in the tier-1 gate, push the rest to -m slow
@pytest.mark.parametrize("conds", [
    [("a", "lt", 500)],
    pytest.param([("a", "ge", 250), ("low", "lt", 40)],
                 marks=pytest.mark.slow),
    [("d", "eq", b"val7")],
    pytest.param([("s", "eq", b"s42")], marks=pytest.mark.slow),
    [("nn", "ge", 100)],                    # null-heavy: nulls must fail
    pytest.param([("a", "lt", 800), ("d", "eq", b"val3"),
                  ("nn", "lt", 900)], marks=pytest.mark.slow),
])
def test_fused_filter_differential(raw, eager, pdf, monkeypatch, conds):
    fused = _scan(raw, monkeypatch, {"SRJT_FUSED_FILTER": "1"},
                  row_predicate=conds)
    ref, n_kept = _ref_filtered(eager, pdf, conds)
    assert getattr(fused, "fused_filter_complete", False)
    assert fused.num_rows == n_kept
    _assert_tables_identical(ref, fused)


def test_fused_filter_off_knob(raw, monkeypatch):
    t = _scan(raw, monkeypatch, {"SRJT_FUSED_FILTER": "0"},
              row_predicate=[("a", "lt", 500)])
    assert not getattr(t, "fused_filter_complete", False)
    assert t.num_rows == N          # predicate ignored: planner reapplies


def test_fused_filter_unsupported_cond_incomplete(raw, eager, pdf,
                                                  monkeypatch):
    # a float conjunct is not host-evaluable → handled subset prunes,
    # ``complete`` stays False so the planner re-applies its mask
    t = _scan(raw, monkeypatch, {"SRJT_FUSED_FILTER": "1"},
              row_predicate=[("a", "lt", 500), ("f", "lt", 0.0)])
    assert not getattr(t, "fused_filter_complete", False)
    ref, _ = _ref_filtered(eager, pdf, [("a", "lt", 500)])
    _assert_tables_identical(ref, t)


def test_planner_skips_reapply_on_full_pushdown(raw, monkeypatch):
    from spark_rapids_jni_tpu import plan as P
    from spark_rapids_jni_tpu.plan import ir
    from spark_rapids_jni_tpu.utils import metrics
    cat = P.FileCatalog({"t": raw})
    tree = ir.Scan("t", columns=("a", "low"),
                   predicate=ir.Cmp("<", ir.Col("a"), ir.Lit(500)))
    metrics.set_enabled(True)
    metrics.reset()
    try:
        monkeypatch.setenv("SRJT_FUSED_FILTER", "1")
        out = P.execute(tree, cat)
        fused_hits = metrics.counter_value("plan.scan.filter_fused")
        monkeypatch.setenv("SRJT_FUSED_FILTER", "0")
        ref = P.execute(tree, cat)
    finally:
        metrics.set_enabled(False)
        monkeypatch.delenv("SRJT_FUSED_FILTER", raising=False)
    assert fused_hits >= 1
    _assert_tables_identical(ref, out)


# --- prefetch ingest attribution ---------------------------------------------


def test_prefetch_ingest_attribution(raw, monkeypatch):
    from spark_rapids_jni_tpu.exec.prefetch import Prefetcher
    from spark_rapids_jni_tpu.utils import metrics
    monkeypatch.setenv("SRJT_STAGE_SLABS", "1")
    metrics.set_enabled(True)
    metrics.reset()
    was = flight.enabled()
    flight.set_enabled(True)
    flight.reset()
    p = Prefetcher(depth=1)
    try:
        assert p.stage("k", lambda: device_scan.scan_table(raw))
        # wait for the STAGING THREAD to finish the load — taking earlier
        # would race it and run the loader inline (a miss, unattributed)
        p._slots["k"]["done"].wait(timeout=60)
        t = p.take("k")
        assert t.num_rows == N
    finally:
        p.close()
        metrics.set_enabled(False)
        flight.set_enabled(was)
    evs = [e for e in flight.events()
           if e["kind"] == "exec.prefetch.ingest"]
    assert evs, "prefetch load did not attribute its staging work"
    assert evs[-1]["slab_bytes"] > 0 and evs[-1]["transfers"] >= 1


# --- donation under the strict sanitizer -------------------------------------


def test_forced_donation_strict_sanitizer(raw, eager, monkeypatch):
    from spark_rapids_jni_tpu.analysis import sanitize
    sanitize.reset()
    try:
        donated = _scan(raw, monkeypatch, {"SRJT_SCAN_DONATE": "1",
                                           "SRJT_SANITIZE": "strict"})
    finally:
        sanitize.reset()
    _assert_tables_identical(eager, donated)


@pytest.mark.slow
def test_forced_donation_with_staging_and_filter(raw, eager, pdf,
                                                 monkeypatch):
    conds = [("a", "lt", 500), ("nn", "ge", 100)]
    ref, n_kept = _ref_filtered(eager, pdf, conds)
    t = _scan(raw, monkeypatch,
              {"SRJT_SCAN_DONATE": "1", "SRJT_STAGE_SLABS": "1",
               "SRJT_FUSED_FILTER": "1"}, row_predicate=conds)
    assert t.num_rows == n_kept
    _assert_tables_identical(ref, t)
