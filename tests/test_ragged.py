"""Tests for the ragged segmented-copy engine (rowconv/ragged.py).

The pytest session pins the CPU backend (tests/conftest.py), so these cover
the XLA fallback formulations — the DMA kernels themselves are validated on
the real chip by ``tools/tpu_check.py``, which byte-compares them against
the same oracles and writes ``PALLAS_TPU_CHECK.json``.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from spark_rapids_jni_tpu.rowconv import ragged


from benchmarks.ragged_data import random_ragged as _random_ragged  # noqa: E402


@pytest.mark.parametrize("n,M,aligned", [(64, 48, True), (301, 64, False),
                                         (257, 33, False)])
def test_pack_unpack_xla_roundtrip(n, M, aligned):
    rng = np.random.default_rng(n)
    dense, offs, flat = _random_ragged(rng, n, M, aligned)
    got_flat = np.asarray(ragged.pack_rows_xla(jnp.asarray(dense), offs))
    np.testing.assert_array_equal(got_flat, flat)
    got_dense = np.asarray(ragged.unpack_rows_xla(jnp.asarray(flat), offs, M))
    np.testing.assert_array_equal(got_dense, dense)


def test_segmented_copy_xla_gappy():
    rng = np.random.default_rng(7)
    S, n = 50000, 300
    src = rng.integers(1, 256, S).astype(np.uint8)
    sizes = rng.integers(0, 60, n)
    gaps = rng.integers(0, 50, n)
    src_offs = np.cumsum(sizes + gaps) - (sizes + gaps)
    dst_offs = np.concatenate([[0], np.cumsum(sizes)])[:-1]
    total = int(sizes.sum())
    expect = np.zeros(total, np.uint8)
    for k in range(n):
        expect[dst_offs[k]:dst_offs[k] + sizes[k]] = \
            src[src_offs[k]:src_offs[k] + sizes[k]]
    got = np.asarray(ragged.segmented_copy_xla(
        jnp.asarray(src), src_offs, dst_offs, sizes, total))
    np.testing.assert_array_equal(got, expect)


def test_dispatchers_use_fallback_on_cpu():
    assert not ragged.dma_supported()
    rng = np.random.default_rng(1)
    dense, offs, flat = _random_ragged(rng, 40, 64)
    np.testing.assert_array_equal(
        np.asarray(ragged.pack(jnp.asarray(dense), offs)), flat)
    np.testing.assert_array_equal(
        np.asarray(ragged.unpack(jnp.asarray(flat), offs, 64)), dense)


def test_u8_u32_wide_helpers():
    rng = np.random.default_rng(2)
    x = rng.integers(0, 256, 4 * 1024).astype(np.uint8)
    w = np.asarray(ragged.u8_to_u32(jnp.asarray(x)))
    np.testing.assert_array_equal(w, x.view(np.uint32))
    back = np.asarray(ragged.u32_to_u8(jnp.asarray(w)))
    np.testing.assert_array_equal(back, x)
