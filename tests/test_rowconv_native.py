"""Differential tests: C++ host transcode engine vs NumPy oracle vs device.

The reference validates two independent engines against each other
(``tests/row_conversion.cpp:49-58,575-584``); here the C++ engine
(``native/rowconv_engine.cpp``), the scalar NumPy oracle
(``rowconv/reference.py``) and the XLA device path must all produce
byte-identical JCUDF rows.
"""

import numpy as np
import pytest

import spark_rapids_jni_tpu as sr
from spark_rapids_jni_tpu import Column, Table, convert_to_rows, convert_from_rows
from spark_rapids_jni_tpu.rowconv import native as cpp
from spark_rapids_jni_tpu.rowconv import reference as ref
from spark_rapids_jni_tpu.rowconv.layout import compute_row_layout

pytestmark = pytest.mark.skipif(not cpp.available(),
                                reason="no C++ toolchain / build failed")


def _fixed_table(n=257, seed=3):
    rng = np.random.default_rng(seed)
    return Table([
        Column.from_numpy(rng.integers(-1000, 1000, n, dtype=np.int64),
                          validity=rng.random(n) < 0.8),
        Column.from_numpy(rng.integers(-100, 100, n, dtype=np.int32)),
        Column.from_numpy(rng.standard_normal(n).astype(np.float32)),
        Column.from_numpy(rng.integers(0, 2, n).astype(np.uint8), sr.bool8),
        Column.from_numpy(rng.integers(-9, 9, n, dtype=np.int8),
                          validity=rng.random(n) < 0.5),
        Column.from_numpy(rng.integers(0, 10**6, n, dtype=np.int32),
                          sr.decimal32(-2)),
    ])


def _string_table(n=131, seed=4):
    rng = np.random.default_rng(seed)
    words = ["", "a", "tpu", "columnar", "x" * 40, "μνξ"]
    return Table([
        Column.from_numpy(rng.integers(0, 1000, n, dtype=np.int32),
                          validity=rng.random(n) < 0.9),
        Column.strings_from_list(
            [None if rng.random() < 0.2 else words[rng.integers(len(words))]
             for _ in range(n)]),
        Column.from_numpy(rng.integers(0, 100, n, dtype=np.int16)),
        Column.strings_from_list(
            [words[rng.integers(len(words))] for _ in range(n)]),
    ])


def test_layout_differential():
    for table in (_fixed_table(8), _string_table(8)):
        layout = compute_row_layout(table.schema)
        starts, vo, fpv, rs = cpp.layout_native(table.schema)
        assert starts == layout.column_starts
        assert vo == layout.validity_offset
        assert fpv == layout.fixed_plus_validity
        assert rs == layout.fixed_row_size


def test_fixed_pack_matches_oracle_and_device():
    t = _fixed_table()
    cb, co = cpp.to_rows_np(t)
    ob, oo = ref.to_rows_np(t)
    np.testing.assert_array_equal(cb, ob)
    np.testing.assert_array_equal(co, oo)
    dev = convert_to_rows(t)
    assert len(dev) == 1
    np.testing.assert_array_equal(dev[0].host_bytes(), cb)


def test_fixed_unpack_roundtrip():
    t = _fixed_table()
    cb, co = cpp.to_rows_np(t)
    back = cpp.from_rows_np(cb, co, t.schema)
    for orig, got in zip(t.columns, back.columns):
        np.testing.assert_array_equal(np.asarray(orig.data),
                                      np.asarray(got.data))
        np.testing.assert_array_equal(
            np.asarray(orig.validity_or_true()),
            np.asarray(got.validity_or_true()))


@pytest.mark.slow
def test_string_pack_matches_oracle_and_device():
    t = _string_table()
    cb, co = cpp.to_rows_np(t)
    ob, oo = ref.to_rows_np(t)
    np.testing.assert_array_equal(cb, ob)
    np.testing.assert_array_equal(co, oo)
    dev = convert_to_rows(t)
    np.testing.assert_array_equal(dev[0].host_bytes(), cb)


def test_string_unpack_roundtrip():
    t = _string_table()
    cb, co = cpp.to_rows_np(t)
    back = cpp.from_rows_np(cb, co, t.schema)
    for orig, got in zip(t.columns, back.columns):
        if orig.dtype.is_variable_width:
            assert orig.to_pylist() == got.to_pylist()
        else:
            np.testing.assert_array_equal(np.asarray(orig.data),
                                          np.asarray(got.data))
        np.testing.assert_array_equal(
            np.asarray(orig.validity_or_true()),
            np.asarray(got.validity_or_true()))


@pytest.mark.slow
def test_cross_engine_roundtrip_device_to_cpp():
    """Rows produced on device decode identically through the C++ engine."""
    t = _string_table(n=64, seed=9)
    dev = convert_to_rows(t)
    rows = np.asarray(dev[0].data)
    offs = np.asarray(dev[0].offsets)
    back_cpp = cpp.from_rows_np(rows, offs, t.schema)
    back_dev = convert_from_rows(dev[0], t.schema)
    for c_cpp, c_dev in zip(back_cpp.columns, back_dev.columns):
        assert c_cpp.to_pylist() == c_dev.to_pylist()


def test_empty_table():
    t = Table([Column.from_numpy(np.zeros(0, dtype=np.int32))])
    cb, co = cpp.to_rows_np(t)
    assert cb.size == 0 and co.tolist() == [0]
    back = cpp.from_rows_np(cb, co, t.schema)
    assert back.num_rows == 0
