import numpy as np
import jax.numpy as jnp

from spark_rapids_jni_tpu.utils import bitmask


def test_pack_bits_matches_numpy_packbits():
    rng = np.random.default_rng(0)
    for n in (1, 7, 8, 9, 63, 64, 1000):
        v = rng.random(n) < 0.5
        got = np.asarray(bitmask.pack_bits(jnp.asarray(v)))
        np.testing.assert_array_equal(got, bitmask.pack_bits_np(v))


def test_unpack_roundtrip():
    rng = np.random.default_rng(1)
    for n in (3, 8, 17, 256):
        v = rng.random(n) < 0.3
        packed = bitmask.pack_bits(jnp.asarray(v))
        back = np.asarray(bitmask.unpack_bits(packed, n))
        np.testing.assert_array_equal(back, v)


def test_pack_bool_matrix_bit_order():
    # bit i of byte b == column b*8+i (RowConversion.java:56-58)
    v = np.zeros((2, 10), dtype=bool)
    v[0, 0] = True   # byte0 bit0
    v[0, 9] = True   # byte1 bit1
    v[1, 7] = True   # byte0 bit7
    got = np.asarray(bitmask.pack_bool_matrix(jnp.asarray(v)))
    np.testing.assert_array_equal(got, [[1, 2], [128, 0]])


def test_pack_unpack_matrix_roundtrip():
    rng = np.random.default_rng(2)
    v = rng.random((37, 21)) < 0.5
    packed = bitmask.pack_bool_matrix(jnp.asarray(v))
    back = np.asarray(bitmask.unpack_bool_matrix(packed, 21))
    np.testing.assert_array_equal(back, v)
