"""Query-level metrics/trace subsystem (utils/metrics.py): registry
semantics, span-tree nesting, Chrome-trace export round-trip, the
disabled-mode zero-event guarantee, and the engine/tape counters a small
end-to-end query must produce."""

import importlib.util
import json
import os

import numpy as np
import pytest

from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.models.compiled import compile_query
from spark_rapids_jni_tpu.ops.groupby import groupby_aggregate
from spark_rapids_jni_tpu.ops.join import inner_join
from spark_rapids_jni_tpu.utils import metrics


@pytest.fixture(autouse=True)
def _metrics_on():
    metrics.set_enabled(True)
    metrics.reset()
    yield
    metrics.reset()
    metrics.set_enabled(None)      # back to the env default (off)


def _load_trace_report():
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "trace_report.py")
    spec = importlib.util.spec_from_file_location("trace_report", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# --- registry semantics ------------------------------------------------------


def test_counter_semantics():
    metrics.count("c")
    metrics.count("c")
    metrics.count("c", 5)
    assert metrics.snapshot()["counters"]["c"] == 7


def test_gauge_and_high_water():
    metrics.gauge("g", 3)
    metrics.gauge("g", 1)
    metrics.gauge_max("hw", 3)
    metrics.gauge_max("hw", 1)
    g = metrics.snapshot()["gauges"]
    assert g["g"] == 1          # plain gauge: last write wins
    assert g["hw"] == 3         # high-water: max survives


def test_histogram_semantics():
    for v in (1, 3, 1000):
        metrics.observe("h", v)
    h = metrics.snapshot()["histograms"]["h"]
    assert h["count"] == 3 and h["total"] == 1004
    assert h["min"] == 1 and h["max"] == 1000
    # log2 buckets: 1 → <=2^1, 3 → <=2^2, 1000 → <=2^10
    assert h["buckets"] == {"<=2^1": 1, "<=2^2": 1, "<=2^10": 1}


def test_span_tree_nesting():
    with metrics.span("root", q="x"):
        with metrics.span("child_a"):
            with metrics.span("leaf"):
                metrics.annotate(rows=7)
        with metrics.span("child_b"):
            pass
    roots = metrics.span_roots()
    assert [r["name"] for r in roots] == ["root"]
    root = roots[0]
    assert root["attrs"] == {"q": "x"}
    assert [c["name"] for c in root["children"]] == ["child_a", "child_b"]
    leaf = root["children"][0]["children"][0]
    assert leaf["name"] == "leaf" and leaf["attrs"] == {"rows": 7}
    assert root["dur_ms"] >= root["children"][0]["dur_ms"] >= 0
    bd = metrics.stage_breakdown()
    assert bd["root"]["count"] == 1 and bd["leaf"]["count"] == 1


def test_disabled_mode_records_nothing():
    metrics.set_enabled(False)
    metrics.count("c")
    metrics.gauge("g", 1)
    metrics.gauge_max("hw", 1)
    metrics.observe("h", 1)
    metrics.ledger_add("p", captures=1)
    with metrics.span("s"):
        metrics.annotate(x=1)
    assert metrics.snapshot() == {"counters": {}, "gauges": {},
                                  "histograms": {}, "ledger": {}}
    assert metrics.span_roots() == []
    # the disabled span context is one SHARED object — no per-call alloc
    assert metrics.span("a") is metrics.span("b")


def test_set_enabled_rereads_env(monkeypatch):
    monkeypatch.setenv("SPARK_RAPIDS_TPU_METRICS", "1")
    metrics.set_enabled(None)
    assert metrics.enabled()
    monkeypatch.setenv("SPARK_RAPIDS_TPU_METRICS", "0")
    metrics.set_enabled(None)
    assert not metrics.enabled()


# --- Chrome-trace export -----------------------------------------------------


def test_chrome_trace_roundtrip(tmp_path):
    metrics.count("events.total", 3)
    with metrics.span("outer"):
        with metrics.span("inner", rows=5):
            pass
    path = metrics.export_chrome_trace(str(tmp_path / "trace.json"))
    with open(path) as f:
        doc = json.load(f)
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"outer", "inner"}
    inner = next(e for e in xs if e["name"] == "inner")
    assert inner["args"] == {"rows": 5}
    outer = next(e for e in xs if e["name"] == "outer")
    # child nests inside the parent on the timeline
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3
    assert doc["srjtCounters"]["events.total"] == 3
    cs = [e for e in doc["traceEvents"] if e["ph"] == "C"]
    assert any(e["name"] == "events.total" for e in cs)

    # the report tool digests the same file
    tr = _load_trace_report()
    events, extras = tr.load_events(path)
    agg = tr.summarize(events)
    assert agg["inner"]["count"] == 1
    assert extras["srjtCounters"]["events.total"] == 3
    assert "inner" in tr.render(agg)


def test_trace_export_default_path_env(tmp_path, monkeypatch):
    monkeypatch.setenv("SPARK_RAPIDS_TPU_METRICS_TRACE",
                       str(tmp_path / "t.json"))
    with metrics.span("s"):
        pass
    assert metrics.export_chrome_trace() == str(tmp_path / "t.json")
    assert (tmp_path / "t.json").exists()


# --- end-to-end: a small query produces the promised counters ---------------


def _tables():
    f = Table([Column.from_numpy(np.arange(64, dtype=np.int64) % 16),
               Column.from_numpy(np.arange(64, dtype=np.int32))])
    d = Table([Column.from_numpy(np.arange(16, dtype=np.int64)),
               Column.from_numpy((np.arange(16, dtype=np.int32) % 4))])
    return {"f": f, "d": d}


def _q(tables):
    j = inner_join(tables["f"], tables["d"], 0, 0)
    # columns: [f.key, f.val, d.key, d.grp] — group by d.grp, sum f.val
    return groupby_aggregate(j, [3], [(1, "sum")])


def test_query_span_tree_and_engine_counters():
    tables = _tables()
    with metrics.query_span("small"):
        _q(tables)
    roots = metrics.span_roots()
    assert roots and roots[-1]["name"] == "query:small"

    names: set[str] = set()

    def walk(s):
        names.add(s["name"])
        for c in s.get("children", ()):
            walk(c)
    walk(roots[-1])
    # children name the join / groupby / sort stages
    assert "join.indices" in names
    assert "groupby.aggregate" in names
    assert "sort.order_by" in names

    c = metrics.snapshot()["counters"]
    # dense int64 keys 0..15 pick the dense direct-lookup engine
    assert c.get("join.engine.dense", 0) >= 1
    assert c.get("join.build_index.cache_miss", 0) >= 1

    # a second eager run probes the SAME build column buffers — memo hit
    _q(tables)
    c = metrics.snapshot()["counters"]
    assert c.get("join.build_index.cache_hit", 0) >= 1


def test_compiled_query_counters():
    tables = _tables()
    cq = compile_query(_q, tables)
    c = metrics.snapshot()["counters"]
    assert c.get("compiled.capture", 0) == 1
    h = metrics.snapshot()["histograms"]
    assert h["compiled.tape_len"]["count"] == 1
    assert h["compiled.tape_len"]["max"] == len(cq.tape)

    out = cq.run(tables)
    assert out.num_rows == 4
    c = metrics.snapshot()["counters"]
    assert c.get("compiled.replay_run", 0) >= 1
    # the replay trace itself records the recompile (in_trace counter)
    assert c.get("compiled.recompile", 0) >= 1
    bd = metrics.stage_breakdown()
    assert any(k.startswith("compiled.run:") for k in bd)
    assert "compiled.dispatch" in bd

    # steady loop with metrics DISABLED takes the raw-dispatch fast path
    metrics.reset()
    metrics.set_enabled(False)
    cq.run_unchecked(tables)
    assert metrics.snapshot()["counters"] == {}


def test_hbm_sampling_records_gauges():
    live = metrics.sample_hbm()
    g = metrics.snapshot()["gauges"]
    assert g["hbm.live_bytes"] == live
    assert g["hbm.live_bytes.peak"] >= live


# --- percentiles (lifetime + rolling window) ---------------------------------


def test_percentile_empty_histogram_returns_none():
    assert metrics.percentile("never_observed", 95) is None
    assert metrics.percentile("never_observed", 95, window_s=60) is None


def test_percentile_single_sample_is_its_own_percentile():
    metrics.observe("solo", 42.0)
    for q in (0, 50, 99, 100):
        assert metrics.percentile("solo", q, window_s=60) == 42.0


def test_windowed_percentile_exact_nearest_rank():
    for v in range(1, 101):                 # 1..100, one each
        metrics.observe("lat", float(v))
    assert metrics.percentile("lat", 50, window_s=60) == 50.0
    assert metrics.percentile("lat", 95, window_s=60) == 95.0
    assert metrics.percentile("lat", 99, window_s=60) == 99.0
    assert metrics.percentile("lat", 100, window_s=60) == 100.0
    # lifetime log2-bucket path: coarse but clamped to observed range
    est = metrics.percentile("lat", 95)
    assert 1.0 <= est <= 100.0


def test_windowed_percentile_excludes_stale_samples():
    metrics.observe("w", 1000.0)
    # a zero-width window sees nothing (all samples are in the past)
    assert metrics.percentile("w", 50, window_s=0) is None
    assert metrics.percentile("w", 50, window_s=60) == 1000.0


def test_counter_value_accessor():
    assert metrics.counter_value("nope") == 0
    metrics.count("yes", 3)
    assert metrics.counter_value("yes") == 3


# --- Prometheus export -------------------------------------------------------

_PROM_LINE = (r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
              r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\"(,[a-zA-Z_]"
              r"[a-zA-Z0-9_]*=\"[^\"]*\")*\})? "
              r"(-?[0-9.e+-]+|\+Inf|-Inf|NaN)$")


def test_to_prometheus_format_and_content():
    import re
    metrics.count("exec.completed", 5)
    metrics.gauge("exec.inflight_bytes", 1024)
    for v in (1.0, 3.0, 100.0):
        metrics.observe("exec.e2e_ms", v)
    text = metrics.to_prometheus()
    pat = re.compile(_PROM_LINE)
    for line in text.splitlines():
        if line.startswith("#"):
            assert re.match(r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* "
                            r"(counter|gauge|histogram)$", line), line
        else:
            assert pat.match(line), f"bad exposition line: {line!r}"
    assert "srjt_exec_completed 5" in text
    assert "srjt_exec_inflight_bytes 1024" in text
    # histogram: cumulative buckets ending at +Inf == count, plus sum
    assert 'srjt_exec_e2e_ms_bucket{le="+Inf"} 3' in text
    assert "srjt_exec_e2e_ms_sum 104" in text
    assert "srjt_exec_e2e_ms_count 3" in text
    cums = [int(line.rsplit(" ", 1)[1]) for line in text.splitlines()
            if line.startswith("srjt_exec_e2e_ms_bucket")]
    assert cums == sorted(cums)             # buckets are cumulative


def test_prometheus_http_endpoint():
    from urllib.request import urlopen
    metrics.count("scraped", 1)
    srv = metrics.start_http_server(0)      # ephemeral port
    try:
        url = f"http://127.0.0.1:{srv.server_port}/metrics"
        resp = urlopen(url, timeout=5)
        assert resp.headers["Content-Type"].startswith("text/plain")
        body = resp.read().decode()
        assert "srjt_scraped 1" in body
        assert urlopen(f"http://127.0.0.1:{srv.server_port}/nope",
                       timeout=5).status if False else True
    finally:
        metrics.stop_http_server()


def test_start_http_server_noop_without_port(monkeypatch):
    monkeypatch.delenv("SRJT_METRICS_PORT", raising=False)
    assert metrics.start_http_server() is None
