"""Query-level metrics/trace subsystem (utils/metrics.py): registry
semantics, span-tree nesting, Chrome-trace export round-trip, the
disabled-mode zero-event guarantee, and the engine/tape counters a small
end-to-end query must produce."""

import importlib.util
import json
import os

import numpy as np
import pytest

from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.models.compiled import compile_query
from spark_rapids_jni_tpu.ops.groupby import groupby_aggregate
from spark_rapids_jni_tpu.ops.join import inner_join
from spark_rapids_jni_tpu.utils import metrics


@pytest.fixture(autouse=True)
def _metrics_on():
    metrics.set_enabled(True)
    metrics.reset()
    yield
    metrics.reset()
    metrics.set_enabled(None)      # back to the env default (off)


def _load_trace_report():
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "trace_report.py")
    spec = importlib.util.spec_from_file_location("trace_report", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# --- registry semantics ------------------------------------------------------


def test_counter_semantics():
    metrics.count("c")
    metrics.count("c")
    metrics.count("c", 5)
    assert metrics.snapshot()["counters"]["c"] == 7


def test_gauge_and_high_water():
    metrics.gauge("g", 3)
    metrics.gauge("g", 1)
    metrics.gauge_max("hw", 3)
    metrics.gauge_max("hw", 1)
    g = metrics.snapshot()["gauges"]
    assert g["g"] == 1          # plain gauge: last write wins
    assert g["hw"] == 3         # high-water: max survives


def test_histogram_semantics():
    for v in (1, 3, 1000):
        metrics.observe("h", v)
    h = metrics.snapshot()["histograms"]["h"]
    assert h["count"] == 3 and h["total"] == 1004
    assert h["min"] == 1 and h["max"] == 1000
    # log2 buckets: 1 → <=2^1, 3 → <=2^2, 1000 → <=2^10
    assert h["buckets"] == {"<=2^1": 1, "<=2^2": 1, "<=2^10": 1}


def test_span_tree_nesting():
    with metrics.span("root", q="x"):
        with metrics.span("child_a"):
            with metrics.span("leaf"):
                metrics.annotate(rows=7)
        with metrics.span("child_b"):
            pass
    roots = metrics.span_roots()
    assert [r["name"] for r in roots] == ["root"]
    root = roots[0]
    assert root["attrs"] == {"q": "x"}
    assert [c["name"] for c in root["children"]] == ["child_a", "child_b"]
    leaf = root["children"][0]["children"][0]
    assert leaf["name"] == "leaf" and leaf["attrs"] == {"rows": 7}
    assert root["dur_ms"] >= root["children"][0]["dur_ms"] >= 0
    bd = metrics.stage_breakdown()
    assert bd["root"]["count"] == 1 and bd["leaf"]["count"] == 1


def test_disabled_mode_records_nothing():
    metrics.set_enabled(False)
    metrics.count("c")
    metrics.gauge("g", 1)
    metrics.gauge_max("hw", 1)
    metrics.observe("h", 1)
    with metrics.span("s"):
        metrics.annotate(x=1)
    assert metrics.snapshot() == {"counters": {}, "gauges": {},
                                  "histograms": {}}
    assert metrics.span_roots() == []
    # the disabled span context is one SHARED object — no per-call alloc
    assert metrics.span("a") is metrics.span("b")


def test_set_enabled_rereads_env(monkeypatch):
    monkeypatch.setenv("SPARK_RAPIDS_TPU_METRICS", "1")
    metrics.set_enabled(None)
    assert metrics.enabled()
    monkeypatch.setenv("SPARK_RAPIDS_TPU_METRICS", "0")
    metrics.set_enabled(None)
    assert not metrics.enabled()


# --- Chrome-trace export -----------------------------------------------------


def test_chrome_trace_roundtrip(tmp_path):
    metrics.count("events.total", 3)
    with metrics.span("outer"):
        with metrics.span("inner", rows=5):
            pass
    path = metrics.export_chrome_trace(str(tmp_path / "trace.json"))
    with open(path) as f:
        doc = json.load(f)
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"outer", "inner"}
    inner = next(e for e in xs if e["name"] == "inner")
    assert inner["args"] == {"rows": 5}
    outer = next(e for e in xs if e["name"] == "outer")
    # child nests inside the parent on the timeline
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3
    assert doc["srjtCounters"]["events.total"] == 3
    cs = [e for e in doc["traceEvents"] if e["ph"] == "C"]
    assert any(e["name"] == "events.total" for e in cs)

    # the report tool digests the same file
    tr = _load_trace_report()
    events, extras = tr.load_events(path)
    agg = tr.summarize(events)
    assert agg["inner"]["count"] == 1
    assert extras["srjtCounters"]["events.total"] == 3
    assert "inner" in tr.render(agg)


def test_trace_export_default_path_env(tmp_path, monkeypatch):
    monkeypatch.setenv("SPARK_RAPIDS_TPU_METRICS_TRACE",
                       str(tmp_path / "t.json"))
    with metrics.span("s"):
        pass
    assert metrics.export_chrome_trace() == str(tmp_path / "t.json")
    assert (tmp_path / "t.json").exists()


# --- end-to-end: a small query produces the promised counters ---------------


def _tables():
    f = Table([Column.from_numpy(np.arange(64, dtype=np.int64) % 16),
               Column.from_numpy(np.arange(64, dtype=np.int32))])
    d = Table([Column.from_numpy(np.arange(16, dtype=np.int64)),
               Column.from_numpy((np.arange(16, dtype=np.int32) % 4))])
    return {"f": f, "d": d}


def _q(tables):
    j = inner_join(tables["f"], tables["d"], 0, 0)
    # columns: [f.key, f.val, d.key, d.grp] — group by d.grp, sum f.val
    return groupby_aggregate(j, [3], [(1, "sum")])


def test_query_span_tree_and_engine_counters():
    tables = _tables()
    with metrics.query_span("small"):
        _q(tables)
    roots = metrics.span_roots()
    assert roots and roots[-1]["name"] == "query:small"

    names: set[str] = set()

    def walk(s):
        names.add(s["name"])
        for c in s.get("children", ()):
            walk(c)
    walk(roots[-1])
    # children name the join / groupby / sort stages
    assert "join.indices" in names
    assert "groupby.aggregate" in names
    assert "sort.order_by" in names

    c = metrics.snapshot()["counters"]
    # dense int64 keys 0..15 pick the dense direct-lookup engine
    assert c.get("join.engine.dense", 0) >= 1
    assert c.get("join.build_index.cache_miss", 0) >= 1

    # a second eager run probes the SAME build column buffers — memo hit
    _q(tables)
    c = metrics.snapshot()["counters"]
    assert c.get("join.build_index.cache_hit", 0) >= 1


def test_compiled_query_counters():
    tables = _tables()
    cq = compile_query(_q, tables)
    c = metrics.snapshot()["counters"]
    assert c.get("compiled.capture", 0) == 1
    h = metrics.snapshot()["histograms"]
    assert h["compiled.tape_len"]["count"] == 1
    assert h["compiled.tape_len"]["max"] == len(cq.tape)

    out = cq.run(tables)
    assert out.num_rows == 4
    c = metrics.snapshot()["counters"]
    assert c.get("compiled.replay_run", 0) >= 1
    # the replay trace itself records the recompile (in_trace counter)
    assert c.get("compiled.recompile", 0) >= 1
    bd = metrics.stage_breakdown()
    assert any(k.startswith("compiled.run:") for k in bd)
    assert "compiled.dispatch" in bd

    # steady loop with metrics DISABLED takes the raw-dispatch fast path
    metrics.reset()
    metrics.set_enabled(False)
    cq.run_unchecked(tables)
    assert metrics.snapshot()["counters"] == {}


def test_hbm_sampling_records_gauges():
    live = metrics.sample_hbm()
    g = metrics.snapshot()["gauges"]
    assert g["hbm.live_bytes"] == live
    assert g["hbm.live_bytes.peak"] >= live
