"""Round-5 query breadth — stddev aggregate, INTERSECT/EXCEPT, DENSE_RANK,
two-level groupby — each compared against pandas running the same plan
over the same parquet bytes (the suite's differential pattern)."""

import io

import numpy as np
import pandas as pd
import pytest

from benchmarks import tpcds_data
from spark_rapids_jni_tpu.models import tpcds


@pytest.fixture(scope="module")
def files():
    return tpcds_data.generate(n_sales=30_000, n_items=400, seed=23)


@pytest.fixture(scope="module")
def dfs(files):
    return {name: pd.read_parquet(io.BytesIO(raw))
            for name, raw in files.items()}


@pytest.fixture(scope="module")
def tables(files):
    return tpcds.load_tables(files)


def test_q17_stats(tables, dfs):
    out = tpcds.q17_stats(tables)
    ss, store = dfs["store_sales"], dfs["store"]
    j = ss.merge(store, left_on="ss_store_sk", right_on="s_store_sk")
    exp = (j.groupby("s_state", as_index=False)
           .agg(m=("ss_quantity", "mean"), sd=("ss_quantity", "std"),
                c=("ss_quantity", "count"))
           .sort_values("s_state").reset_index(drop=True))
    assert out[0].to_pylist() == exp.s_state.tolist()
    np.testing.assert_allclose(np.asarray(out[1].to_numpy(), np.float64),
                               exp.m.to_numpy(), rtol=1e-9)
    # pandas std is the sample std (ddof=1) — the Spark STDDEV default
    np.testing.assert_allclose(np.asarray(out[2].to_numpy(), np.float64),
                               exp.sd.to_numpy(), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(out[3].to_numpy()),
                                  exp.c.to_numpy())


def test_q8_intersect(tables, dfs):
    out = tpcds.q8_intersect(tables)
    ss, ws, item = dfs["store_sales"], dfs["web_sales"], dfs["item"]
    js = ss.merge(item, left_on="ss_item_sk", right_on="i_item_sk")
    jw = ws.merge(item, left_on="ws_item_sk", right_on="i_item_sk")
    exp = np.sort(np.intersect1d(js.i_category_id.unique(),
                                 jw.i_category_id.unique()))
    np.testing.assert_array_equal(np.asarray(out[0].to_numpy()), exp)


def test_q87_except(tables, dfs):
    out = tpcds.q87_except(tables)
    ss, ws, item = dfs["store_sales"], dfs["web_sales"], dfs["item"]
    js = ss.merge(item, left_on="ss_item_sk", right_on="i_item_sk")
    jw = ws.merge(item, left_on="ws_item_sk", right_on="i_item_sk")
    exp = np.sort(np.setdiff1d(js.i_brand_id.unique(),
                               jw.i_brand_id.unique()))
    np.testing.assert_array_equal(np.asarray(out[0].to_numpy()), exp)


def test_q_dense_rank_cat(tables, dfs):
    out = tpcds.q_dense_rank_cat(tables)
    ss, item, dd = dfs["store_sales"], dfs["item"], dfs["date_dim"]
    j = (ss.merge(item, left_on="ss_item_sk", right_on="i_item_sk")
         .merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk"))
    rev = (j.groupby(["i_category", "d_moy"], as_index=False)
           ["ss_ext_sales_price"].sum())
    rev["dr"] = (rev.groupby("i_category")["ss_ext_sales_price"]
                 .rank(method="dense", ascending=False).astype(int))
    exp = (rev[rev.dr <= 2]
           .sort_values(["i_category", "dr", "d_moy"])
           .reset_index(drop=True))
    assert out.num_rows == len(exp)
    assert out[0].to_pylist() == exp.i_category.tolist()
    np.testing.assert_array_equal(np.asarray(out[1].to_numpy()),
                                  exp.d_moy.to_numpy())
    np.testing.assert_allclose(np.asarray(out[2].to_numpy(), np.float64),
                               exp.ss_ext_sales_price.to_numpy(),
                               rtol=1e-9)
    np.testing.assert_array_equal(np.asarray(out[3].to_numpy()),
                                  exp.dr.to_numpy())


def test_q34_baskets(tables, dfs):
    out = tpcds.q34_baskets(tables)
    ss = dfs["store_sales"]
    per_item = (ss.groupby(["ss_store_sk", "ss_item_sk"], as_index=False)
                ["ss_quantity"].sum())
    big = per_item[per_item.ss_quantity >= 60]
    exp = (big.groupby("ss_store_sk", as_index=False)["ss_item_sk"]
           .count().sort_values("ss_store_sk").reset_index(drop=True))
    assert out.num_rows == len(exp)
    np.testing.assert_array_equal(np.asarray(out[0].to_numpy()),
                                  exp.ss_store_sk.to_numpy())
    np.testing.assert_array_equal(np.asarray(out[1].to_numpy()),
                                  exp.ss_item_sk.to_numpy())
