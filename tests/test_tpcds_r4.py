"""Round-4 TPC-DS breadth differentials: rollup / grouping sets / cube,
multi-fact outer joins, disjunctive bands, semi/anti, selection aggregates,
window dedup — every query compared against pandas running the same plan
(same parquet bytes), like tests/test_tpcds.py."""

import io

import numpy as np
import pandas as pd
import pytest

from benchmarks import tpcds_data
from spark_rapids_jni_tpu.models import tpcds


@pytest.fixture(scope="module")
def files():
    return tpcds_data.generate(n_sales=40_000, n_items=500, seed=7)


@pytest.fixture(scope="module")
def dfs(files):
    return {name: pd.read_parquet(io.BytesIO(raw))
            for name, raw in files.items()}


@pytest.fixture(scope="module")
def tables(files):
    return tpcds.load_tables(files)


def _vals(col):
    if col.dtype.id.name == "STRING":
        return col.to_pylist()
    return col.to_numpy().tolist()


def _rollup_expect(j, keys, val, gid_levels):
    """pandas grouping-sets union with Spark grouping_id + null keys."""
    frames = []
    for included, gid in gid_levels:
        if included:
            g = (j.groupby([keys[i] for i in included], as_index=False,
                           dropna=False)[val].sum())
        else:
            g = pd.DataFrame({val: [j[val].sum()]})
        for i, k in enumerate(keys):
            if i not in included:
                g[k] = None
        g["gid"] = gid
        frames.append(g[keys + [val, "gid"]])
    return pd.concat(frames, ignore_index=True)


@pytest.mark.slow
def test_q36_rollup(tables, dfs):
    out = tpcds.q36_rollup(tables)
    ss, item = dfs["store_sales"], dfs["item"]
    j = ss.merge(item, left_on="ss_item_sk", right_on="i_item_sk")
    keys = ["i_category", "i_brand"]
    exp = _rollup_expect(j, keys, "ss_ext_sales_price",
                         [([0, 1], 0), ([0], 1), ([], 3)])
    exp = exp.sort_values(["gid"] + keys,
                          na_position="first").reset_index(drop=True)
    assert out.num_rows == len(exp)
    # row-by-row on (gid, keys, sum)
    got_gid = out[3].to_numpy().tolist()
    assert got_gid == exp["gid"].tolist()
    got_cat = out[0].to_pylist()
    exp_cat = [None if pd.isna(v) else v for v in exp["i_category"]]
    assert got_cat == exp_cat
    np.testing.assert_allclose(out[2].to_numpy(),
                               exp["ss_ext_sales_price"].to_numpy(),
                               rtol=1e-9)


def test_q86_rollup(tables, dfs):
    out = tpcds.q86_rollup(tables)
    ss, dd = dfs["store_sales"], dfs["date_dim"]
    j = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
    exp = _rollup_expect(j, ["d_year", "d_moy"], "ss_ext_sales_price",
                         [([0, 1], 0), ([0], 1), ([], 3)])
    exp = exp.sort_values(["gid", "d_year", "d_moy"],
                          na_position="first").reset_index(drop=True)
    assert out.num_rows == len(exp)
    assert out[3].to_numpy().tolist() == exp["gid"].tolist()
    np.testing.assert_allclose(out[2].to_numpy(),
                               exp["ss_ext_sales_price"].to_numpy(),
                               rtol=1e-9)


def test_q27_cube(tables, dfs):
    out = tpcds.q27_cube(tables)
    ss, item, store = dfs["store_sales"], dfs["item"], dfs["store"]
    j = (ss.merge(item, left_on="ss_item_sk", right_on="i_item_sk")
         .merge(store, left_on="ss_store_sk", right_on="s_store_sk"))
    frames = []
    for included, gid in [([0, 1], 0), ([0], 1), ([1], 2), ([], 3)]:
        keys = ["i_category", "s_state"]
        if included:
            g = j.groupby([keys[i] for i in included], as_index=False).agg(
                qmean=("ss_quantity", "mean"),
                psum=("ss_ext_sales_price", "sum"))
        else:
            g = pd.DataFrame({"qmean": [j.ss_quantity.mean()],
                              "psum": [j.ss_ext_sales_price.sum()]})
        for i, k in enumerate(keys):
            if i not in included:
                g[k] = None
        g["gid"] = gid
        frames.append(g[keys + ["qmean", "psum", "gid"]])
    exp = pd.concat(frames, ignore_index=True).sort_values(
        ["gid", "i_category", "s_state"],
        na_position="first").reset_index(drop=True)
    assert out.num_rows == len(exp)
    assert out[4].to_numpy().tolist() == exp["gid"].tolist()
    np.testing.assert_allclose(out[2].to_numpy(), exp["qmean"].to_numpy(),
                               rtol=1e-9)
    np.testing.assert_allclose(out[3].to_numpy(), exp["psum"].to_numpy(),
                               rtol=1e-9)


def test_q5_grouping_sets(tables, dfs):
    out = tpcds.q5_grouping_sets(tables)
    ss, ws, item = dfs["store_sales"], dfs["web_sales"], dfs["item"]
    s = ss[["ss_item_sk", "ss_ext_sales_price"]].rename(
        columns={"ss_item_sk": "item_sk", "ss_ext_sales_price": "price"})
    s["channel"] = 0
    w = ws[["ws_item_sk", "ws_ext_sales_price"]].rename(
        columns={"ws_item_sk": "item_sk", "ws_ext_sales_price": "price"})
    w["channel"] = 1
    both = pd.concat([s, w], ignore_index=True)
    j = both.merge(item, left_on="item_sk", right_on="i_item_sk")
    frames = []
    for included, gid in [([0, 1], 0), ([0], 1), ([], 3)]:
        keys = ["channel", "i_category"]
        if included:
            g = (j.groupby([keys[i] for i in included],
                           as_index=False)["price"].sum())
        else:
            g = pd.DataFrame({"price": [j["price"].sum()]})
        for i, k in enumerate(keys):
            if i not in included:
                g[k] = None
        g["gid"] = gid
        frames.append(g[keys + ["price", "gid"]])
    exp = pd.concat(frames, ignore_index=True).sort_values(
        ["gid", "channel", "i_category"],
        na_position="first").reset_index(drop=True)
    assert out.num_rows == len(exp)
    assert out[3].to_numpy().tolist() == exp["gid"].tolist()
    np.testing.assert_allclose(out[2].to_numpy(), exp["price"].to_numpy(),
                               rtol=1e-9)


def test_q78_outer(tables, dfs):
    out = tpcds.q78_outer(tables)
    ss, ws = dfs["store_sales"], dfs["web_sales"]
    s = ss.groupby("ss_item_sk", as_index=False)["ss_ext_sales_price"].sum()
    w = ws.groupby("ws_item_sk", as_index=False)["ws_ext_sales_price"].sum()
    m = s.merge(w, left_on="ss_item_sk", right_on="ws_item_sk", how="outer")
    m["key"] = m["ss_item_sk"].fillna(m["ws_item_sk"]).astype(np.int64)
    m["s"] = m["ss_ext_sales_price"].fillna(0.0)
    m["w"] = m["ws_ext_sales_price"].fillna(0.0)
    exp = m.sort_values("key").reset_index(drop=True)
    assert out.num_rows == len(exp)
    assert out[0].to_numpy().astype(np.int64).tolist() == exp["key"].tolist()
    np.testing.assert_allclose(out[1].to_numpy(), exp["s"].to_numpy(),
                               rtol=1e-9)
    np.testing.assert_allclose(out[2].to_numpy(), exp["w"].to_numpy(),
                               rtol=1e-9)


def test_q25_two_fact(tables, dfs):
    out = tpcds.q25_two_fact(tables, year=2000)
    ss, ws, dd = dfs["store_sales"], dfs["web_sales"], dfs["date_dim"]
    ddf = dd[dd.d_year == 2000]
    js = ss.merge(ddf, left_on="ss_sold_date_sk", right_on="d_date_sk")
    jw = ws.merge(ddf, left_on="ws_sold_date_sk", right_on="d_date_sk")
    s = js.groupby("ss_item_sk", as_index=False)["ss_ext_sales_price"].sum()
    w = jw.groupby("ws_item_sk", as_index=False)["ws_ext_sales_price"].sum()
    m = s.merge(w, left_on="ss_item_sk", right_on="ws_item_sk")
    exp = m.sort_values("ss_item_sk").reset_index(drop=True)
    assert out.num_rows == len(exp)
    np.testing.assert_allclose(out[1].to_numpy(),
                               exp["ss_ext_sales_price"].to_numpy(),
                               rtol=1e-9)
    np.testing.assert_allclose(out[2].to_numpy(),
                               exp["ws_ext_sales_price"].to_numpy(),
                               rtol=1e-9)


def test_q88_counts(tables, dfs):
    out = tpcds.q88_counts(tables)
    q = dfs["store_sales"].ss_quantity
    exp = [int(((q >= lo) & (q <= hi)).sum())
           for lo, hi in [(1, 25), (26, 50), (51, 75), (76, 100)]]
    got = [int(out[i].to_numpy()[0]) for i in range(4)]
    assert got == exp


def test_q90_ratio(tables, dfs):
    out = tpcds.q90_ratio(tables)
    ss, dd = dfs["store_sales"], dfs["date_dim"]
    j = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
    am = int((j.d_moy <= 6).sum())
    pm = int((j.d_moy > 6).sum())
    assert int(out[0].to_numpy()[0]) == am
    assert int(out[1].to_numpy()[0]) == pm
    np.testing.assert_allclose(out[2].to_numpy()[0], am / max(pm, 1),
                               rtol=1e-6)


def test_q29_minmax(tables, dfs):
    out = tpcds.q29_minmax(tables)
    ss, item = dfs["store_sales"], dfs["item"]
    j = ss.merge(item, left_on="ss_item_sk", right_on="i_item_sk")
    exp = j.groupby("i_brand_id", as_index=False).agg(
        qmin=("ss_quantity", "min"), qmax=("ss_quantity", "max"),
        qmean=("ss_quantity", "mean")).sort_values(
            "i_brand_id").reset_index(drop=True)
    assert out.num_rows == len(exp)
    assert out[1].to_numpy().tolist() == exp["qmin"].tolist()
    assert out[2].to_numpy().tolist() == exp["qmax"].tolist()
    np.testing.assert_allclose(out[3].to_numpy(), exp["qmean"].to_numpy(),
                               rtol=1e-9)


def test_q48_bands(tables, dfs):
    out = tpcds.q48_bands(tables)
    ss, store = dfs["store_sales"], dfs["store"]
    q, p = ss.ss_quantity, ss.ss_sales_price_cents
    m = (((q >= 1) & (q <= 20) & (p < 50_00))
         | ((q >= 41) & (q <= 60) & (p > 150_00)))
    j = ss[m].merge(store, left_on="ss_store_sk", right_on="s_store_sk")
    exp = (j.groupby("s_state", as_index=False)["ss_quantity"].sum()
           .sort_values("s_state").reset_index(drop=True))
    assert out.num_rows == len(exp)
    assert out[0].to_pylist() == exp["s_state"].tolist()
    assert out[1].to_numpy().tolist() == exp["ss_quantity"].tolist()


def test_q13_avg_bands(tables, dfs):
    out = tpcds.q13_avg_bands(tables)
    ss = dfs["store_sales"]
    for i, (lo, hi) in enumerate([(1, 33), (34, 66), (67, 100)]):
        sel = ss[(ss.ss_quantity >= lo) & (ss.ss_quantity <= hi)]
        exp = sel.ss_sales_price_cents.mean() / 100.0
        np.testing.assert_allclose(out[i].to_numpy()[0], exp, rtol=1e-9)


def test_q96_count(tables, dfs):
    out = tpcds.q96_count(tables, year=2000, qty_min=80)
    ss, dd = dfs["store_sales"], dfs["date_dim"]
    j = ss[ss.ss_quantity >= 80].merge(dd[dd.d_year == 2000],
                                       left_on="ss_sold_date_sk",
                                       right_on="d_date_sk")
    assert int(out[0].to_numpy()[0]) == len(j)
    assert int(out[1].to_numpy()[0]) == int(j.ss_quantity.sum())


def test_q23_semi(tables, dfs):
    out = tpcds.q23_semi(tables, min_sales=30)
    ss = dfs["store_sales"]
    cnt = ss.groupby("ss_item_sk")["ss_item_sk"].count()
    freq = set(cnt[cnt > 30].index)
    hits = ss[ss.ss_item_sk.isin(freq)]
    np.testing.assert_allclose(out[0].to_numpy()[0],
                               hits.ss_ext_sales_price.sum(), rtol=1e-9)
    assert int(out[1].to_numpy()[0]) == len(hits)


def test_q16_anti(tables, dfs):
    out = tpcds.q16_anti(tables)
    ss, item = dfs["store_sales"], dfs["item"]
    sold = set(ss.ss_item_sk.unique())
    unsold = item[~item.i_item_sk.isin(sold)].sort_values("i_item_sk")
    assert out[0].to_numpy().tolist() == unsold["i_item_sk"].tolist()
    assert out[1].to_numpy().tolist() == unsold["i_manufact_id"].tolist()


def test_q_minmax_price(tables, dfs):
    out = tpcds.q_minmax_price(tables)
    item = dfs["item"]
    exp = item.groupby("i_category", as_index=False).agg(
        pmin=("i_current_price", "min"),
        pmax=("i_current_price", "max")).sort_values(
            "i_category").reset_index(drop=True)
    assert out[0].to_pylist() == exp["i_category"].tolist()
    # decimal32(-2): unscaled cents
    np.testing.assert_allclose(out[1].to_numpy() / 100.0,
                               exp["pmin"].astype(float).to_numpy(),
                               rtol=1e-9)
    np.testing.assert_allclose(out[2].to_numpy() / 100.0,
                               exp["pmax"].astype(float).to_numpy(),
                               rtol=1e-9)


def test_q_multi_measure(tables, dfs):
    out = tpcds.q_multi_measure(tables)
    ss = dfs["store_sales"]
    exp = ss.groupby("ss_store_sk", as_index=False).agg(
        qsum=("ss_quantity", "sum"), psum=("ss_sales_price_cents", "sum"),
        lmean=("ss_list_price_cents", "mean")).sort_values(
            "ss_store_sk").reset_index(drop=True)
    assert out.num_rows == len(exp)
    assert out[1].to_numpy().tolist() == exp["qsum"].tolist()
    assert out[2].to_numpy().tolist() == exp["psum"].tolist()
    np.testing.assert_allclose(out[3].to_numpy(), exp["lmean"].to_numpy(),
                               rtol=1e-9)


def test_q_rollup3(tables, dfs):
    out = tpcds.q_rollup3(tables)
    ss, dd, store = dfs["store_sales"], dfs["date_dim"], dfs["store"]
    j = (ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(store, left_on="ss_store_sk", right_on="s_store_sk"))
    # four levels: (y,m,s)=0, (y,m)=1, (y)=3, ()=7
    n_exp = (len(j.groupby(["d_year", "d_moy", "s_state"]))
             + len(j.groupby(["d_year", "d_moy"]))
             + len(j.groupby(["d_year"])) + 1)
    assert out.num_rows == n_exp
    # grand total row: gid 7
    gids = out[4].to_numpy()
    total_rows = out[3].to_numpy()[gids == 7]
    np.testing.assert_allclose(total_rows[0],
                               j.ss_ext_sales_price.sum(), rtol=1e-9)


def test_q_first_last(tables, dfs):
    out = tpcds.q_first_last(tables)
    ss = dfs["store_sales"]
    srt = ss.sort_values("ss_sold_date_sk", kind="stable")
    exp = srt.groupby("ss_item_sk", as_index=False).agg(
        first=("ss_sales_price_cents", "first"),
        last=("ss_sales_price_cents", "last")).sort_values(
            "ss_item_sk").reset_index(drop=True)
    assert out.num_rows == len(exp)
    # first/last within equal-date ties may differ between stable sorts;
    # compare against the set of prices at the boundary date per item
    got_first = out[1].to_numpy()
    got_last = out[2].to_numpy()
    date_by_item_min = srt.groupby("ss_item_sk")["ss_sold_date_sk"].min()
    date_by_item_max = srt.groupby("ss_item_sk")["ss_sold_date_sk"].max()
    keys = exp["ss_item_sk"].tolist()
    grp = dict(tuple(ss.groupby("ss_item_sk")))
    for i, k in enumerate(keys):
        g = grp[k]
        ok_first = set(
            g[g.ss_sold_date_sk == date_by_item_min[k]]
            .ss_sales_price_cents)
        ok_last = set(
            g[g.ss_sold_date_sk == date_by_item_max[k]]
            .ss_sales_price_cents)
        assert got_first[i] in ok_first
        assert got_last[i] in ok_last


def test_q_rownum_dedup(tables, dfs):
    out = tpcds.q_rownum_dedup(tables, keep=2)
    ss, dd = dfs["store_sales"], dfs["date_dim"]
    j = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
    rev = j.groupby(["ss_store_sk", "d_moy"],
                    as_index=False)["ss_ext_sales_price"].sum()
    rev["rn"] = (rev.sort_values(["ss_ext_sales_price", "d_moy"],
                                 ascending=[False, True])
                 .groupby("ss_store_sk").cumcount() + 1)
    exp = (rev[rev.rn <= 2].sort_values(["ss_store_sk", "rn"])
           .reset_index(drop=True))
    assert out.num_rows == len(exp)
    np.testing.assert_allclose(out[2].to_numpy(),
                               exp["ss_ext_sales_price"].to_numpy(),
                               rtol=1e-9)


def test_q_cross_ratio(tables, dfs):
    out = tpcds.q_cross_ratio(tables)
    ss, ws, item = dfs["store_sales"], dfs["web_sales"], dfs["item"]
    js = ss.merge(item, left_on="ss_item_sk", right_on="i_item_sk")
    jw = ws.merge(item, left_on="ws_item_sk", right_on="i_item_sk")
    s = js.groupby("i_category")["ss_ext_sales_price"].sum()
    w = jw.groupby("i_category")["ws_ext_sales_price"].sum()
    cats = sorted(set(s.index) & set(w.index))
    assert out[0].to_pylist() == cats
    np.testing.assert_allclose(
        out[3].to_numpy(),
        np.asarray([w[c] / s[c] for c in cats]), rtol=1e-9)


def test_q_null_share(tables, dfs):
    out = tpcds.q_null_share(tables)
    ws, item = dfs["web_sales"], dfs["item"]
    j = ws.merge(item, left_on="ws_item_sk", right_on="i_item_sk")
    exp = j.groupby("i_category", as_index=False).agg(
        n=("ws_item_sk", "count"), nn=("ws_ext_sales_price", "count"),
        s=("ws_ext_sales_price", "sum")).sort_values(
            "i_category").reset_index(drop=True)
    assert out[0].to_pylist() == exp["i_category"].tolist()
    assert out[1].to_numpy().tolist() == exp["n"].tolist()
    assert out[2].to_numpy().tolist() == exp["nn"].tolist()
    # nulls actually present → the two counts must differ somewhere
    assert (exp["n"] != exp["nn"]).any()
    np.testing.assert_allclose(out[3].to_numpy(), exp["s"].to_numpy(),
                               rtol=1e-9)


@pytest.mark.slow
def test_run_all_includes_new_queries(files):
    results = tpcds.run_all(files)
    assert len(results) >= 41
    assert set(tpcds.QUERIES) == set(results)
