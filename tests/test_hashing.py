"""Murmur3 x86_32 tests: vectorized JAX implementation vs an independent
scalar implementation written directly from the public MurmurHash3 spec."""

import numpy as np
import jax.numpy as jnp

from spark_rapids_jni_tpu.ops import hashing


def _scalar_murmur3_bytes(data: bytes, seed: int) -> int:
    """Scalar MurmurHash3 x86_32 (public-domain algorithm, Austin Appleby)."""
    c1, c2 = 0xCC9E2D51, 0x1B873593
    mask = 0xFFFFFFFF

    def rotl(x, r):
        return ((x << r) | (x >> (32 - r))) & mask

    h = seed & mask
    nblocks = len(data) // 4
    for i in range(nblocks):
        k = int.from_bytes(data[4 * i:4 * i + 4], "little")
        k = (k * c1) & mask
        k = rotl(k, 15)
        k = (k * c2) & mask
        h ^= k
        h = rotl(h, 13)
        h = (h * 5 + 0xE6546B64) & mask
    # (no tail for 4/8-byte keys)
    h ^= len(data)
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & mask
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & mask
    h ^= h >> 16
    return h


def test_murmur3_int32_matches_scalar_spec():
    vals = np.asarray([0, 1, -1, 42, 2**31 - 1, -2**31], dtype=np.int32)
    got = np.asarray(hashing.murmur3_32(jnp.asarray(vals)))
    for v, g in zip(vals, got):
        expect = _scalar_murmur3_bytes(
            int(v).to_bytes(4, "little", signed=True), 42)
        assert int(g) == expect, v


def test_murmur3_int64_matches_scalar_spec():
    vals = np.asarray([0, 1, -1, 2**40, -2**40, 2**63 - 1], dtype=np.int64)
    got = np.asarray(hashing.murmur3_32(jnp.asarray(vals)))
    for v, g in zip(vals, got):
        expect = _scalar_murmur3_bytes(
            int(v).to_bytes(8, "little", signed=True), 42)
        assert int(g) == expect, v


def test_small_ints_sign_extend_like_spark():
    # Spark hashes ByteType/ShortType by sign-extending to a 4-byte int
    a = np.asarray(hashing.murmur3_32(jnp.asarray(np.asarray([-3], np.int8))))
    b = np.asarray(hashing.murmur3_32(jnp.asarray(np.asarray([-3], np.int32))))
    assert a[0] == b[0]


def test_hash_partition_non_negative_and_stable():
    h = hashing.murmur3_32(jnp.arange(1000, dtype=jnp.int64))
    p = np.asarray(hashing.hash_partition(h, 8))
    assert p.min() >= 0 and p.max() < 8
    # roughly uniform: each partition gets something
    assert len(np.unique(p)) == 8


def test_float32_hashes_by_bit_pattern_with_spark_normalization():
    import struct
    vals = np.asarray([1.5, -0.0, 0.0, np.nan, np.inf], dtype=np.float32)
    got = np.asarray(hashing.murmur3_32(jnp.asarray(vals)))
    def bits(f):
        if np.isnan(f):
            return 0x7FC00000
        if f == 0.0:
            f = 0.0  # -0.0 normalized
        return struct.unpack("<I", struct.pack("<f", f))[0]
    for v, g in zip(vals, got):
        expect = _scalar_murmur3_bytes(int(bits(v)).to_bytes(4, "little"), 42)
        assert int(g) == expect, v
    assert got[1] == got[2]  # -0.0 == 0.0


def test_float64_keys_rejected():
    import pytest
    with pytest.raises(TypeError, match="float64"):
        hashing.murmur3_32(jnp.asarray(np.asarray([1.0], dtype=np.float64)))
