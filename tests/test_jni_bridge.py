"""Drive the JNI bridge entry points through a ctypes-built mock JNIEnv.

The reference tests its JNI surface from JUnit through a real JVM
(RowConversionTest.java:29-59); without a JDK in this image, we construct
the JNI function table ourselves (slot numbers per the JNI 6 spec, matching
native/jni_min.h) and call the JNIEXPORT functions directly — exercising
handle unwrapping, schema marshalling, the column-release protocol, and
exception translation.
"""

import ctypes as C

import numpy as np
import pytest

import spark_rapids_jni_tpu as sr
from spark_rapids_jni_tpu.native import load

lib = load()
pytestmark = pytest.mark.skipif(lib is None, reason="libsrjt.so unavailable")

# JNI 6 slot numbers (jni_min.h)
SLOTS = 233
S_FINDCLASS, S_THROWNEW = 6, 14
S_GETSTRINGUTF, S_RELEASESTRINGUTF = 169, 170
S_GETARRAYLEN, S_GETOBJARRAYELT = 171, 173
S_NEWLONGARRAY = 180
S_GETINTREGION, S_GETLONGREGION = 203, 204
S_SETLONGREGION = 212

VOIDP = C.c_void_p


class MockEnv:
    """A JNINativeInterface_ table + object registry standing in for a JVM."""

    def __init__(self):
        self.objects = {}       # id -> python object ("jobject" handles)
        self.next_id = 1
        self.thrown = None      # (class_name, message)
        self._cbs = []          # keep callbacks alive
        table = (VOIDP * SLOTS)()

        def reg(obj):
            oid = self.next_id
            self.next_id += 1
            self.objects[oid] = obj
            return oid

        self.register = reg

        def put(slot, restype, argtypes, fn):
            cb = C.CFUNCTYPE(restype, *argtypes)(fn)
            self._cbs.append(cb)
            table[slot] = C.cast(cb, VOIDP)

        put(S_FINDCLASS, C.c_void_p, [VOIDP, C.c_char_p],
            lambda env, name: reg(("class", name.decode())))
        put(S_THROWNEW, C.c_int32, [VOIDP, C.c_void_p, C.c_char_p],
            self._throw_new)
        put(S_GETSTRINGUTF, C.c_void_p, [VOIDP, C.c_void_p, VOIDP],
            self._get_string_utf)
        put(S_RELEASESTRINGUTF, None, [VOIDP, C.c_void_p, C.c_char_p],
            lambda env, s, chars: None)
        put(S_GETARRAYLEN, C.c_int32, [VOIDP, C.c_void_p],
            lambda env, arr: len(self.objects[arr]))
        put(S_GETOBJARRAYELT, C.c_void_p, [VOIDP, C.c_void_p, C.c_int32],
            lambda env, arr, i: self.objects[arr][i])
        put(S_NEWLONGARRAY, C.c_void_p, [VOIDP, C.c_int32],
            lambda env, n: reg([0] * n))
        put(S_GETINTREGION, None,
            [VOIDP, C.c_void_p, C.c_int32, C.c_int32, C.POINTER(C.c_int32)],
            self._get_region)
        put(S_GETLONGREGION, None,
            [VOIDP, C.c_void_p, C.c_int32, C.c_int32, C.POINTER(C.c_int64)],
            self._get_region)
        put(S_SETLONGREGION, None,
            [VOIDP, C.c_void_p, C.c_int32, C.c_int32, C.POINTER(C.c_int64)],
            self._set_long_region)

        self._table = table
        # JNIEnv* = pointer to (pointer to table)
        self._table_p = C.cast(table, VOIDP)
        self.env = C.pointer(self._table_p)
        self._utf_bufs = []

    def _throw_new(self, env, cls, msg):
        self.thrown = (self.objects[cls][1], msg.decode())
        return 0

    def _get_string_utf(self, env, s, is_copy):
        buf = C.create_string_buffer(self.objects[s].encode())
        self._utf_bufs.append(buf)
        return C.cast(buf, VOIDP).value

    def _get_region(self, env, arr, start, n, out):
        vals = self.objects[arr]
        for i in range(n):
            out[i] = vals[start + i]

    def _set_long_region(self, env, arr, start, n, vals):
        target = self.objects[arr]
        for i in range(n):
            target[start + i] = vals[i]

    # helpers to build "jarray"/"jstring" handles
    def long_array(self, vals):
        return self.register([int(v) for v in vals])

    def int_array(self, vals):
        return self.register([int(v) for v in vals])

    def string_array(self, strs):
        return self.register([self.register(s) for s in strs])


def _fn(name, restype, argtypes):
    f = getattr(lib, name)
    f.restype = restype
    f.argtypes = argtypes
    return f


ENVP = C.POINTER(VOIDP)


def test_row_conversion_round_trip_through_jni():
    env = MockEnv()
    make_fixed = _fn("Java_com_tpu_rapids_jni_HostColumn_makeFixed",
                     C.c_int64, [ENVP, VOIDP, C.c_int32, C.c_int32,
                                 C.c_int64, C.c_int64, C.c_int64])
    make_table = _fn("Java_com_tpu_rapids_jni_HostTable_makeTable",
                     C.c_int64, [ENVP, VOIDP, C.c_void_p])
    to_rows = _fn("Java_com_tpu_rapids_jni_RowConversion_convertToRows",
                  C.c_int64, [ENVP, VOIDP, C.c_int64])
    from_rows = _fn("Java_com_tpu_rapids_jni_RowConversion_convertFromRows",
                    C.c_int64, [ENVP, VOIDP, C.c_int64, C.c_int32,
                                C.c_void_p, C.c_void_p])
    tbl_columns = _fn("Java_com_tpu_rapids_jni_HostTable_columns",
                      C.c_void_p, [ENVP, VOIDP, C.c_int64])
    col_close = _fn("Java_com_tpu_rapids_jni_HostColumn_close",
                    None, [ENVP, VOIDP, C.c_int64])
    tbl_close = _fn("Java_com_tpu_rapids_jni_HostTable_close",
                    None, [ENVP, VOIDP, C.c_int64])
    rows_free = _fn("Java_com_tpu_rapids_jni_RowConversion_freeRows",
                    None, [ENVP, VOIDP, C.c_int64])
    col_data = _fn("srjt_column_data", C.POINTER(C.c_uint8), [C.c_void_p])
    col_valid = _fn("srjt_column_valid", C.POINTER(C.c_uint8), [C.c_void_p])
    col_rows = _fn("srjt_column_rows", C.c_int64, [C.c_void_p])

    rng = np.random.default_rng(0)
    n = 1000
    i64 = rng.integers(-(2**60), 2**60, n, dtype=np.int64)
    i32 = rng.integers(-(2**30), 2**30, n, dtype=np.int32)
    valid32 = (rng.random(n) < 0.9).astype(np.uint8)

    h64 = make_fixed(env.env, None, int(sr.int64.id), 0, n,
                     i64.ctypes.data, 0)
    h32 = make_fixed(env.env, None, int(sr.int32.id), 0, n,
                     i32.ctypes.data, valid32.ctypes.data)
    assert h64 and h32 and env.thrown is None

    th = make_table(env.env, None, env.long_array([h64, h32]))
    assert th and env.thrown is None

    rows = to_rows(env.env, None, th)
    assert rows and env.thrown is None

    out_th = from_rows(env.env, None, rows, 0,
                       env.int_array([int(sr.int64.id), int(sr.int32.id)]),
                       env.int_array([0, 0]))
    assert out_th and env.thrown is None

    cols_arr = tbl_columns(env.env, None, out_th)
    handles = env.objects[cols_arr]
    assert len(handles) == 2

    got64 = np.ctypeslib.as_array(col_data(C.c_void_p(handles[0])),
                                  shape=(n * 8,)).view(np.int64)
    np.testing.assert_array_equal(got64, i64)
    got32 = np.ctypeslib.as_array(col_data(C.c_void_p(handles[1])),
                                  shape=(n * 4,)).view(np.int32)
    gotv = np.ctypeslib.as_array(col_valid(C.c_void_p(handles[1])),
                                 shape=(n,))
    np.testing.assert_array_equal(gotv, valid32)
    np.testing.assert_array_equal(got32[valid32 == 1], i32[valid32 == 1])
    assert col_rows(C.c_void_p(handles[0])) == n

    for h in handles:
        col_close(env.env, None, h)
    rows_free(env.env, None, rows)
    tbl_close(env.env, None, th)
    tbl_close(env.env, None, out_th)
    col_close(env.env, None, h64)
    col_close(env.env, None, h32)


def test_row_size_limit_throws_java_exception():
    env = MockEnv()
    make_fixed = _fn("Java_com_tpu_rapids_jni_HostColumn_makeFixed",
                     C.c_int64, [ENVP, VOIDP, C.c_int32, C.c_int32,
                                 C.c_int64, C.c_int64, C.c_int64])
    make_table = _fn("Java_com_tpu_rapids_jni_HostTable_makeTable",
                     C.c_int64, [ENVP, VOIDP, C.c_void_p])
    to_rows = _fn("Java_com_tpu_rapids_jni_RowConversion_convertToRows",
                  C.c_int64, [ENVP, VOIDP, C.c_int64])

    n = 8
    data = np.zeros(n, dtype=np.int64)
    handles = [make_fixed(env.env, None, int(sr.int64.id), 0, n,
                          data.ctypes.data, 0) for _ in range(200)]
    th = make_table(env.env, None, env.long_array(handles))
    out = to_rows(env.env, None, th)  # 200*8B + validity > 1KB
    assert out == 0
    assert env.thrown is not None
    assert env.thrown[0] == "java/lang/IllegalArgumentException"
    assert "1KB" in env.thrown[1]


def test_string_round_trip_through_jni():
    env = MockEnv()
    make_string = _fn("Java_com_tpu_rapids_jni_HostColumn_makeString",
                      C.c_int64, [ENVP, VOIDP, C.c_int64, C.c_int64,
                                  C.c_int64, C.c_int64])
    make_fixed = _fn("Java_com_tpu_rapids_jni_HostColumn_makeFixed",
                     C.c_int64, [ENVP, VOIDP, C.c_int32, C.c_int32,
                                 C.c_int64, C.c_int64, C.c_int64])
    make_table = _fn("Java_com_tpu_rapids_jni_HostTable_makeTable",
                     C.c_int64, [ENVP, VOIDP, C.c_void_p])
    to_rows = _fn("Java_com_tpu_rapids_jni_RowConversion_convertToRows",
                  C.c_int64, [ENVP, VOIDP, C.c_int64])
    from_rows = _fn("Java_com_tpu_rapids_jni_RowConversion_convertFromRows",
                    C.c_int64, [ENVP, VOIDP, C.c_int64, C.c_int32,
                                C.c_void_p, C.c_void_p])
    tbl_columns = _fn("Java_com_tpu_rapids_jni_HostTable_columns",
                      C.c_void_p, [ENVP, VOIDP, C.c_int64])
    col_data = _fn("srjt_column_data", C.POINTER(C.c_uint8), [C.c_void_p])
    col_offsets = _fn("srjt_column_offsets", C.POINTER(C.c_int32),
                      [C.c_void_p])
    col_data_size = _fn("srjt_column_data_size", C.c_int64, [C.c_void_p])

    strs = ["hello", "", "tpu", "jcudf rows", "x" * 40]
    n = len(strs)
    chars = "".join(strs).encode()
    offsets = np.zeros(n + 1, dtype=np.int32)
    offsets[1:] = np.cumsum([len(s.encode()) for s in strs])
    chars_np = np.frombuffer(chars, dtype=np.uint8).copy()
    ints = np.arange(n, dtype=np.int32)

    hs = make_string(env.env, None, n, offsets.ctypes.data,
                     chars_np.ctypes.data, 0)
    hi = make_fixed(env.env, None, int(sr.int32.id), 0, n,
                    ints.ctypes.data, 0)
    th = make_table(env.env, None, env.long_array([hs, hi]))
    rows = to_rows(env.env, None, th)
    assert rows and env.thrown is None

    out_th = from_rows(env.env, None, rows, 0,
                       env.int_array([int(sr.string.id), int(sr.int32.id)]),
                       None)
    assert out_th and env.thrown is None
    handles = env.objects[tbl_columns(env.env, None, out_th)]
    offs = np.ctypeslib.as_array(col_offsets(C.c_void_p(handles[0])),
                                 shape=(n + 1,))
    np.testing.assert_array_equal(offs, offsets)
    size = col_data_size(C.c_void_p(handles[0]))
    got_chars = np.ctypeslib.as_array(col_data(C.c_void_p(handles[0])),
                                      shape=(size,))
    assert bytes(got_chars) == chars


def test_parquet_footer_through_jni():
    from spark_rapids_jni_tpu.parquet import (StructElement, ValueElement,
                                              read_and_filter)
    from spark_rapids_jni_tpu.parquet.footer import extract_footer_bytes
    from test_parquet_footer import simple_file

    data = extract_footer_bytes(simple_file(n=10))
    schema = StructElement("root", ValueElement("a"))
    expected = read_and_filter(data, 0, 1 << 30, schema)

    env = MockEnv()
    read_filter = _fn("Java_com_tpu_rapids_jni_ParquetFooter_readAndFilter",
                      C.c_int64, [ENVP, VOIDP, C.c_int64, C.c_int64,
                                  C.c_int64, C.c_int64, C.c_void_p,
                                  C.c_void_p, C.c_void_p, C.c_int32,
                                  C.c_uint8])
    num_rows = _fn("Java_com_tpu_rapids_jni_ParquetFooter_getNumRows",
                   C.c_int64, [ENVP, VOIDP, C.c_int64])
    num_cols = _fn("Java_com_tpu_rapids_jni_ParquetFooter_getNumColumns",
                   C.c_int64, [ENVP, VOIDP, C.c_int64])
    serialize = _fn(
        "Java_com_tpu_rapids_jni_ParquetFooter_serializeThriftFile",
        C.c_int64, [ENVP, VOIDP, C.c_int64, C.c_int64, C.c_int64])
    close = _fn("Java_com_tpu_rapids_jni_ParquetFooter_close",
                None, [ENVP, VOIDP, C.c_int64])

    buf = np.frombuffer(data, dtype=np.uint8).copy()
    flat_names, flat_nc, flat_tags = schema.flatten_depth_first()
    names = env.string_array(flat_names)
    nc = env.int_array(flat_nc)
    tags = env.int_array(flat_tags)

    h = read_filter(env.env, None, buf.ctypes.data, len(data), 0, 1 << 30,
                    names, nc, tags, len(schema.children), 0)
    assert env.thrown is None and h
    assert num_rows(env.env, None, h) == expected.num_rows == 10
    assert num_cols(env.env, None, h) == expected.num_columns == 1

    want = expected.serialize_thrift_file()
    out = np.zeros(len(want) + 64, dtype=np.uint8)
    written = serialize(env.env, None, h, out.ctypes.data, len(out))
    assert bytes(out[:written]) == want   # byte-identical to the python engine
    close(env.env, None, h)
