"""Window functions — differential vs pandas groupby windows."""

import numpy as np
import pandas as pd
import pytest

from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.ops import window as W


def _data(n=400, parts=7, seed=0):
    rng = np.random.default_rng(seed)
    part = rng.integers(0, parts, n).astype(np.int32)
    order_key = rng.integers(0, 50, n).astype(np.int64)
    vals = rng.integers(-100, 100, n).astype(np.int64)
    valid = rng.random(n) < 0.85
    t = Table([Column.from_numpy(part), Column.from_numpy(order_key),
               Column.from_numpy(vals, validity=valid)])
    df = pd.DataFrame({"p": part, "o": order_key,
                       "v": np.where(valid, vals, np.nan)})
    return t, df


@pytest.fixture(scope="module")
def spec_and_df():
    t, df = _data()
    return W.WindowSpec(t, [0], [1]), df


def test_row_number(spec_and_df):
    spec, df = spec_and_df
    got = np.asarray(W.row_number(spec).data)
    # pandas: stable sort by (p, o) then cumcount within p
    df2 = df.copy()
    df2["rn"] = (df.sort_values(["p", "o"], kind="stable")
                 .groupby("p").cumcount() + 1)
    np.testing.assert_array_equal(got, df2["rn"].to_numpy())


def test_rank_and_dense_rank(spec_and_df):
    spec, df = spec_and_df
    got_r = np.asarray(W.rank(spec, [1]).data)
    got_d = np.asarray(W.dense_rank(spec, [1]).data)
    want_r = df.groupby("p")["o"].rank(method="min").to_numpy()
    want_d = df.groupby("p")["o"].rank(method="dense").to_numpy()
    np.testing.assert_array_equal(got_r, want_r.astype(np.int64))
    np.testing.assert_array_equal(got_d, want_d.astype(np.int64))


def test_running_sum_and_count(spec_and_df):
    spec, df = spec_and_df
    got = np.asarray(W.running_sum(spec, 2).data)
    got_c = np.asarray(W.running_count(spec, 2).data)
    df2 = df.sort_values(["p", "o"], kind="stable").copy()
    df2["rs"] = df2.groupby("p")["v"].transform(
        lambda s: s.fillna(0).cumsum())
    df2["rc"] = df2.groupby("p")["v"].transform(
        lambda s: s.notna().cumsum())
    back = df2.sort_index()
    np.testing.assert_array_equal(got, back["rs"].to_numpy().astype(np.int64))
    np.testing.assert_array_equal(got_c, back["rc"].to_numpy())


def test_lag_lead_roundtrip():
    # deterministic tiny case with string partitions
    part = Column.strings_from_list(["a", "b", "a", "b", "a"])
    order_key = Column.from_numpy(np.asarray([1, 1, 2, 2, 3], np.int64))
    vals = Column.from_numpy(np.asarray([10, 20, 30, 40, 50], np.int64))
    t = Table([part, order_key, vals])
    spec = W.WindowSpec(t, [0], [1])
    assert W.lag(spec, 2).to_pylist() == [None, None, 10, 20, 30]
    assert W.lead(spec, 2).to_pylist() == [30, 40, 50, None, None]
    assert W.lag(spec, 2, offset=2).to_pylist() == [None, None, None, None, 10]


def test_lag_null_values_stay_null():
    part = Column.from_numpy(np.zeros(3, np.int32))
    order_key = Column.from_numpy(np.arange(3, dtype=np.int64))
    vals = Column.from_numpy(np.asarray([1, 0, 3], np.int64),
                             validity=np.asarray([True, False, True]))
    spec = W.WindowSpec(Table([part, order_key, vals]), [0], [1])
    assert W.lag(spec, 2).to_pylist() == [None, 1, None]


def test_descending_order():
    part = Column.from_numpy(np.zeros(4, np.int32))
    order_key = Column.from_numpy(np.asarray([1, 2, 3, 4], np.int64))
    vals = Column.from_numpy(np.asarray([10, 20, 30, 40], np.int64))
    spec = W.WindowSpec(Table([part, order_key, vals]), [0], [1],
                        ascending=[False])
    got = np.asarray(W.row_number(spec).data)
    np.testing.assert_array_equal(got, [4, 3, 2, 1])


class TestReviewRegressions:
    def test_rank_null_order_key_is_distinct(self):
        # NULL order key vs a valid row with the same stored payload:
        # Spark ranks them separately (null sorts first)
        part = Column.from_numpy(np.zeros(2, np.int32))
        ok = Column.from_numpy(np.zeros(2, np.int64),
                               validity=np.asarray([False, True]))
        t = Table([part, ok])
        spec = W.WindowSpec(t, [0], [1])
        assert np.asarray(W.rank(spec, [1]).data).tolist() == [1, 2]
        assert np.asarray(W.dense_rank(spec, [1]).data).tolist() == [1, 2]

    def test_running_sum_decimal128_rejected(self):
        from spark_rapids_jni_tpu.ops import decimal128 as d128
        col = d128.from_pyints([1, 2])
        t = Table([Column.from_numpy(np.zeros(2, np.int32)),
                   Column.from_numpy(np.arange(2, dtype=np.int64)), col])
        spec = W.WindowSpec(t, [0], [1])
        with pytest.raises(TypeError, match="DECIMAL128"):
            W.running_sum(spec, 2)

    def test_running_min_max_match_pandas(self):
        rng = np.random.default_rng(3)
        n = 300
        part = rng.integers(0, 6, n).astype(np.int32)
        ok = rng.integers(0, 40, n).astype(np.int64)
        vals = rng.integers(-90, 90, n).astype(np.int64)
        valid = rng.random(n) < 0.8
        t = Table([Column.from_numpy(part), Column.from_numpy(ok),
                   Column.from_numpy(vals, validity=valid)])
        spec = W.WindowSpec(t, [0], [1])
        df = pd.DataFrame({"p": part, "o": ok,
                           "v": np.where(valid, vals.astype(float), np.nan)})
        srt = df.sort_values(["p", "o"], kind="stable")
        want_max = srt.groupby("p")["v"].cummax().sort_index().to_numpy()
        want_min = srt.groupby("p")["v"].cummin().sort_index().to_numpy()
        got_max = np.asarray(W.running_max(spec, 2).data).astype(float)
        got_min = np.asarray(W.running_min(spec, 2).data).astype(float)
        np.testing.assert_array_equal(got_max[valid], want_max[valid])
        np.testing.assert_array_equal(got_min[valid], want_min[valid])

    def test_null_partition_keys_form_one_partition(self):
        # both rows NULL with DIFFERENT dead payloads: one Spark partition
        part = Column.from_numpy(np.asarray([5, 7], np.int32),
                                 validity=np.asarray([False, False]))
        ok = Column.from_numpy(np.asarray([1, 2], np.int64))
        t = Table([part, ok])
        spec = W.WindowSpec(t, [0], [1])
        assert np.asarray(W.row_number(spec).data).tolist() == [1, 2]

    def test_null_order_keys_tie_despite_payloads(self):
        part = Column.from_numpy(np.zeros(2, np.int32))
        ok = Column.from_numpy(np.asarray([3, 9], np.int64),
                               validity=np.asarray([False, False]))
        spec = W.WindowSpec(Table([part, ok]), [0], [1])
        assert np.asarray(W.rank(spec, [1]).data).tolist() == [1, 1]
        assert np.asarray(W.dense_rank(spec, [1]).data).tolist() == [1, 1]
