"""Nested (LIST/STRUCT) column tests.

The reference gets nested columns from libcudf (SURVEY §2.9: lists columns
``make_lists_column`` row_conversion.cu:1264, structs columns); JCUDF row
conversion itself rejects them (row_conversion.cu:1268-1271).  These tests
cover the TPU-native column hierarchy: construction, host round-trip,
gather/filter through arbitrary nesting, and the rowconv rejection contract.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_jni_tpu import types as T
from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.ops import apply_boolean_mask, gather, mask_table
from spark_rapids_jni_tpu.rowconv import convert_to_rows
from spark_rapids_jni_tpu.rowconv.layout import compute_row_layout


class TestListColumn:
    def test_roundtrip_int(self):
        vals = [[1, 2, 3], [], None, [7], [8, 9]]
        col = Column.list_from_pylist(vals)
        assert col.dtype.id == T.TypeId.LIST
        assert col.num_rows == 5
        assert col.to_pylist() == [[1, 2, 3], [], None, [7], [8, 9]]

    def test_roundtrip_strings(self):
        vals = [["ab", "c"], None, [], ["xyz"]]
        col = Column.list_from_pylist(vals)
        assert col.dtype.children[0].id == T.TypeId.STRING
        assert col.to_pylist() == [["ab", "c"], None, [], ["xyz"]]

    def test_roundtrip_list_of_list(self):
        vals = [[[1], [2, 3]], [], None, [[4, 5, 6]]]
        col = Column.list_from_pylist(vals)
        assert col.dtype.children[0].id == T.TypeId.LIST
        assert col.to_pylist() == [[[1], [2, 3]], [], None, [[4, 5, 6]]]

    def test_gather(self):
        col = Column.list_from_pylist([[1, 2], [3], [], [4, 5, 6], None])
        t = gather(Table([col]), jnp.asarray([3, 0, 4]))
        assert t[0].to_pylist() == [[4, 5, 6], [1, 2], None]

    def test_gather_nested_list(self):
        col = Column.list_from_pylist([[["a", "bb"]], [["c"], []], None])
        t = gather(Table([col]), jnp.asarray([1, 0]))
        assert t[0].to_pylist() == [[["c"], []], [["a", "bb"]]]

    def test_boolean_mask(self):
        col = Column.list_from_pylist([[1], [2, 2], [3], [4, 4]])
        ints = Column.from_numpy(np.arange(4, dtype=np.int32))
        t = apply_boolean_mask(Table([ints, col]),
                               jnp.asarray([True, False, True, False]))
        assert t[1].to_pylist() == [[1], [3]]


class TestStructColumn:
    def _make(self):
        a = Column.from_numpy(np.asarray([1, 2, 3], np.int32))
        s = Column.strings_from_list(["x", None, "zz"])
        return Column.struct_from_columns([a, s],
                                          validity=np.asarray([True, True, False]))

    def test_roundtrip(self):
        col = self._make()
        assert col.dtype.id == T.TypeId.STRUCT
        assert col.num_rows == 3
        assert col.to_pylist() == [(1, "x"), (2, None), None]

    def test_gather(self):
        t = gather(Table([self._make()]), jnp.asarray([2, 0]))
        assert t[0].to_pylist() == [None, (1, "x")]

    def test_struct_of_list(self):
        lists = Column.list_from_pylist([[1, 2], [], [3]])
        col = Column.struct_from_columns([lists])
        t = gather(Table([col]), jnp.asarray([2, 0]))
        assert t[0].to_pylist() == [([3],), ([1, 2],)]

    def test_unequal_fields_rejected(self):
        a = Column.from_numpy(np.asarray([1, 2], np.int32))
        b = Column.from_numpy(np.asarray([1], np.int32))
        with pytest.raises(ValueError):
            Column.struct_from_columns([a, b])

    def test_mask_table_keeps_children(self):
        t = mask_table(Table([self._make()]), jnp.asarray([True, False, True]))
        assert t[0].to_pylist() == [(1, "x"), None, None]


class TestDTypeValidation:
    def test_list_requires_one_child(self):
        with pytest.raises(ValueError):
            T.DType(T.TypeId.LIST)

    def test_struct_requires_fields(self):
        with pytest.raises(ValueError):
            T.DType(T.TypeId.STRUCT)

    def test_leaf_rejects_children(self):
        with pytest.raises(ValueError):
            T.DType(T.TypeId.INT32, 0, (T.int64,))


class TestRowconvRejectsNested:
    def test_layout_rejects_list(self):
        with pytest.raises(TypeError, match="LIST"):
            compute_row_layout([T.int32, T.list_(T.int32)])

    def test_convert_rejects_struct(self):
        col = Column.struct_from_columns(
            [Column.from_numpy(np.asarray([1], np.int32))])
        with pytest.raises(TypeError, match="STRUCT"):
            convert_to_rows(Table([col]))
