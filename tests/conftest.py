"""Test harness config: run everything on a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; sharding paths are validated on
``--xla_force_host_platform_device_count=8`` per the project test strategy
(the driver separately dry-run-compiles the multichip path via
``__graft_entry__.dryrun_multichip``).

The environment may pre-register a TPU PJRT plugin via sitecustomize and pin
``JAX_PLATFORMS``; ``jax.config.update`` after import wins over both, as long
as it runs before the backend is initialized (hence this top-level conftest).
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Persistent executable cache (same dir tools/query_bench.py uses): the
# per-module clear_caches below drops live executables to bound XLA:CPU
# memory, so heavyweight programs (capture/replay traces, fused scans,
# the mortgage ETL) recompile once per module — with the disk cache those
# recompiles deserialize instead, keyed on HLO, across modules AND runs.
# Absolute path (was a cwd-relative ".jax_cache", which silently forked a
# fresh cold cache whenever pytest ran from another directory), and shared
# with the AOT artifact-store layout: with SRJT_AOT_DIR set the executables
# land in its `xla/` subdir — the same place exec/artifacts.py points
# serving processes — so test and serving caches compose instead of
# double-compiling.
_aot_dir = os.environ.get("SRJT_AOT_DIR")
_jax_cache = os.path.join(_aot_dir, "xla") if _aot_dir else os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    ".jax_cache")
jax.config.update("jax_compilation_cache_dir", _jax_cache)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


import gc

import pytest


@pytest.fixture(scope="module", autouse=True)
def _clear_jax_caches_per_module():
    """Cap compiled-executable memory across the (large) suite: two full
    runs segfaulted inside XLA:CPU's backend_compile around the ~85% mark
    with hundreds of live executables; dropping caches between modules
    trades some recompiles for a bounded footprint."""
    yield
    jax.clear_caches()
    gc.collect()
