"""Distributed star-join aggregate over the 8-device CPU mesh, differential
vs the single-device op library and pandas."""

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

from spark_rapids_jni_tpu.column import Column, Table
from spark_rapids_jni_tpu.ops import groupby_aggregate, inner_join
from spark_rapids_jni_tpu.parallel.dist_query import (Dimension,
                                                      distributed_star_agg,
                                                      prepare_dimension)
from spark_rapids_jni_tpu.parallel.mesh import make_mesh


def _data(n=8 * 1000, m=64, groups=7, seed=0):
    rng = np.random.default_rng(seed)
    dim_keys = rng.choice(10_000, size=m, replace=False).astype(np.int64)
    dim_groups = [f"g{v}" for v in rng.integers(0, groups, m)]
    # ~1/3 of fact keys miss the dimension (inner-join filtering)
    fact_key = np.where(rng.random(n) < 0.67,
                        rng.choice(dim_keys, size=n),
                        rng.integers(20_000, 30_000, n)).astype(np.int64)
    fact_val = rng.integers(-100, 100, n).astype(np.int64)
    return dim_keys, dim_groups, fact_key, fact_val


def test_matches_pandas_and_single_device():
    dim_keys, dim_groups, fact_key, fact_val = _data()
    dim = prepare_dimension(
        Column.from_numpy(dim_keys),
        Column.strings_from_list(dim_groups))
    mesh = make_mesh(8)
    sums, cnts = distributed_star_agg(mesh, dim, jnp.asarray(fact_key),
                                      jnp.asarray(fact_val))

    # pandas oracle
    dd = pd.DataFrame({"k": dim_keys, "g": dim_groups})
    ff = pd.DataFrame({"k": fact_key, "v": fact_val})
    exp = (ff.merge(dd, on="k").groupby("g")
           .agg(s=("v", "sum"), c=("v", "count")))
    # map group name → code (order-preserving rank over distinct strings)
    code_of = {g: i for i, g in enumerate(sorted(set(dim_groups)))}
    got_s = np.asarray(sums)
    got_c = np.asarray(cnts)
    for g, row in exp.iterrows():
        assert got_s[code_of[g]] == row.s, g
        assert got_c[code_of[g]] == row.c, g
    # groups with no surviving rows are zero
    assert got_s.shape == (dim.num_groups,)

    # single-device op-library oracle (inner_join + groupby)
    fact_t = Table([Column.from_numpy(fact_key), Column.from_numpy(fact_val)])
    dim_t = Table([Column.from_numpy(dim_keys),
                   Column.strings_from_list(dim_groups)])
    j = inner_join(fact_t, dim_t, 0, 0)
    gb = groupby_aggregate(j, [3], [(1, "sum"), (1, "count")])
    for g, s, c in zip(gb[0].to_pylist(), gb[1].to_pylist(),
                       gb[2].to_pylist()):
        assert got_s[code_of[g]] == s
        assert got_c[code_of[g]] == c


def test_integer_group_dimension():
    rng = np.random.default_rng(1)
    dim_keys = np.arange(10, dtype=np.int64)
    dim_groups = Column.from_numpy(
        rng.integers(100, 103, 10).astype(np.int32))
    dim = prepare_dimension(Column.from_numpy(dim_keys), dim_groups)
    assert dim.num_groups <= 3
    fact_key = rng.integers(0, 12, 8 * 16).astype(np.int64)  # some miss
    fact_val = np.ones(8 * 16, dtype=np.int64)
    mesh = make_mesh(8)
    sums, cnts = distributed_star_agg(mesh, dim, jnp.asarray(fact_key),
                                      jnp.asarray(fact_val))
    assert int(np.asarray(cnts).sum()) == int((fact_key < 10).sum())
    np.testing.assert_array_equal(np.asarray(sums), np.asarray(cnts))


def test_runs_under_jit_without_host_sync():
    # the whole program must trace: wrap in an outer jit and assert no
    # TracerArrayConversionError (a host sync inside would raise)
    dim_keys, dim_groups, fact_key, fact_val = _data(n=8 * 32, m=16)
    dim = prepare_dimension(Column.from_numpy(dim_keys),
                            Column.strings_from_list(dim_groups))
    mesh = make_mesh(8)

    @jax.jit
    def run(fk, fv):
        return distributed_star_agg(mesh, dim, fk, fv)

    sums, cnts = run(jnp.asarray(fact_key), jnp.asarray(fact_val))
    assert sums.shape == (dim.num_groups,)


def test_duplicate_dimension_keys_rejected():
    import pytest
    with pytest.raises(ValueError, match="unique"):
        prepare_dimension(
            Column.from_numpy(np.asarray([1, 1, 2], np.int64)),
            Column.from_numpy(np.asarray([0, 1, 0], np.int32)))


def test_compiled_program_is_cached():
    from spark_rapids_jni_tpu.parallel.dist_query import _compiled_star_agg
    mesh = make_mesh(8)
    assert (_compiled_star_agg(mesh, 5, "data")
            is _compiled_star_agg(mesh, 5, "data"))
    assert (_compiled_star_agg(mesh, 5, "data")
            is not _compiled_star_agg(mesh, 6, "data"))


def test_2d_multihost_mesh():
    # 2 hosts x 4 chips: shard over both axes, reduce ICI then DCN
    from spark_rapids_jni_tpu.parallel.mesh import make_2d_mesh
    mesh = make_2d_mesh(2, 4)
    rng = np.random.default_rng(7)
    dim = prepare_dimension(
        Column.from_numpy(np.arange(20, dtype=np.int64)),
        Column.from_numpy((np.arange(20) % 4).astype(np.int32)))
    fact_key = rng.integers(0, 25, 8 * 64).astype(np.int64)
    fact_val = rng.integers(-10, 10, 8 * 64).astype(np.int64)
    sums, cnts = distributed_star_agg(mesh, dim, jnp.asarray(fact_key),
                                      jnp.asarray(fact_val),
                                      axis_name=("dcn", "ici"))
    hit = fact_key < 20
    assert int(np.asarray(cnts).sum()) == int(hit.sum())
    assert int(np.asarray(sums).sum()) == int(fact_val[hit].sum())
    # per-group check vs numpy
    for g in range(dim.num_groups):
        sel = hit & ((fact_key % 4) == g)
        assert int(np.asarray(sums)[g]) == int(fact_val[sel].sum())
