"""EXPLAIN ANALYZE / per-plan-node profiling: differential sweep.

The profiler must be observation-only: profiled execution bit-identical
to unprofiled, per-node observed row counts exact against a pandas
oracle evaluating the same optimized tree, disabled mode one bool check
(node_enter must return before touching any other state), and
capture/replay must take identical branches with ``SRJT_PROFILE=1`` —
including the ``SRJT_PROFILE_VALIDITY`` scalar syncs, which land on the
tape in the same order on capture and replay.
"""

import io
import json

import numpy as np
import pandas as pd
import pytest

from spark_rapids_jni_tpu.column import Column, Table, force_column
from spark_rapids_jni_tpu.models import tpcds_plans
from spark_rapids_jni_tpu.plan import ir, lower, profile
from spark_rapids_jni_tpu.plan import stats as plan_stats
from spark_rapids_jni_tpu.utils import flight, metrics

QUERIES = ("q3", "q52", "q55")         # 3 TPC-DS plan queries (oracle)


def _col(a, validity=None):
    return Column.from_numpy(np.asarray(a), validity=validity)


def _assert_tables_equal(a, b):
    """Bit-identical: same columns, same payload arrays (no reordering
    slack — profiling must be observation-only)."""
    A = [np.asarray(force_column(c).data) for c in a.columns]
    B = [np.asarray(force_column(c).data) for c in b.columns]
    assert len(A) == len(B)
    for i, (x, y) in enumerate(zip(A, B)):
        np.testing.assert_array_equal(x, y, err_msg=f"col {i}")


@pytest.fixture
def prof_on():
    profile.set_enabled(True)
    profile.reset()
    yield profile
    profile.set_enabled(None)


@pytest.fixture(scope="module")
def tpcds():
    """Small TPC-DS tables, device + pandas twins."""
    from benchmarks import tpcds_data
    from spark_rapids_jni_tpu.models import tpcds as M
    files = tpcds_data.generate(n_sales=20_000, n_items=300, seed=11)
    tables = M.load_tables(files)
    pdt = {k: pd.read_parquet(io.BytesIO(v)) for k, v in files.items()}
    return tables, pdt


# --- pandas plan evaluator (row-count oracle) --------------------------------


def _pd_expr(e, df):
    if isinstance(e, ir.Col):
        return df[e.name]
    if isinstance(e, ir.Lit):
        return e.value
    if isinstance(e, ir.Mul):
        return _pd_expr(e.left, df) * _pd_expr(e.right, df)
    if isinstance(e, ir.ScalarAgg):
        s = _pd_expr(e.arg, df)
        return s.mean() if e.fn == "mean" else s.sum()
    raise NotImplementedError(type(e).__name__)


def _pd_mask(p, df):
    if isinstance(p, ir.And):
        m = np.ones(len(df), bool)
        for q in p.parts:
            m &= np.asarray(_pd_mask(q, df))
        return m
    if isinstance(p, ir.Or):
        m = np.zeros(len(df), bool)
        for q in p.parts:
            m |= np.asarray(_pd_mask(q, df))
        return m
    if isinstance(p, ir.Cmp):
        a, b = _pd_expr(p.left, df), _pd_expr(p.right, df)
        import operator as op
        f = {"==": op.eq, "!=": op.ne, "<": op.lt, "<=": op.le,
             ">": op.gt, ">=": op.ge}[p.op]
        return np.asarray(f(a, b))
    if isinstance(p, ir.Between):
        v = _pd_expr(p.col, df)
        m = np.ones(len(df), bool)
        if p.lo is not None:
            m &= np.asarray(v >= p.lo)
        if p.hi is not None:
            m &= np.asarray(v < p.hi if p.hi_strict else v <= p.hi)
        return m
    if isinstance(p, ir.IsIn):
        return np.asarray(_pd_expr(p.col, df).isin(list(p.values)))
    raise NotImplementedError(type(p).__name__)


def _pd_agg(df, keys, aggs):
    g = df.groupby(list(keys), sort=True)
    out = {}
    for src, fn, name in aggs:
        out[name] = g[src].mean() if fn == "mean" else g[src].sum()
    return pd.DataFrame(out).reset_index()


def _pd_eval(node, pdt):
    """Pandas twin of ``lower._apply_node`` — row counts must match the
    profiled execution node for node."""
    if isinstance(node, ir.Scan):
        df = pdt[node.table]
        if node.columns is not None:
            df = df[list(node.columns)]
        if node.predicate is not None:
            df = df[_pd_mask(node.predicate, df)]
        return df.reset_index(drop=True)
    if isinstance(node, ir.Filter):
        df = _pd_eval(node.child, pdt)
        return df[_pd_mask(node.predicate, df)].reset_index(drop=True)
    if isinstance(node, ir.Project):
        return _pd_eval(node.child, pdt)[list(node.columns)]
    if isinstance(node, ir.Join):
        lt, rt = _pd_eval(node.left, pdt), _pd_eval(node.right, pdt)
        return lt.merge(rt, left_on=list(node.left_on),
                        right_on=list(node.right_on), how=node.how)
    if isinstance(node, ir.FusedJoinAggregate):
        lt, rt = _pd_eval(node.left, pdt), _pd_eval(node.right, pdt)
        j = lt.merge(rt, left_on=list(node.left_on),
                     right_on=list(node.right_on), how=node.how)
        return _pd_agg(j, node.keys, node.aggs)
    if isinstance(node, ir.Aggregate):
        return _pd_agg(_pd_eval(node.child, pdt), node.keys, node.aggs)
    if isinstance(node, ir.Sort):
        return _pd_eval(node.child, pdt)
    if isinstance(node, ir.Limit):
        return _pd_eval(node.child, pdt).head(node.n)
    raise NotImplementedError(type(node).__name__)


# --- differential sweep ------------------------------------------------------


@pytest.mark.parametrize("qname", QUERIES)
def test_profiled_bit_identical_and_rows_match_oracle(tpcds, prof_on,
                                                      qname):
    tables, pdt = tpcds
    tree = tpcds_plans.optimized(qname).tree
    cat = lower.TableCatalog(tables, tpcds_plans.TABLE_SCHEMAS)

    profile.set_enabled(False)
    plain = lower.execute(tree, cat, record_stats=False)
    profile.set_enabled(True)
    with profile.query(qname, ir.fingerprint(tree)) as pr:
        got = lower.execute(
            tree, lower.TableCatalog(tables, tpcds_plans.TABLE_SCHEMAS),
            record_stats=False)

    _assert_tables_equal(got, plain)           # bit-identical

    # profile tree mirrors the executed tree; every node's observed rows
    # must equal the pandas evaluation of the same subtree
    assert len(pr.roots) == 1

    def check(rec, node):
        kids = ir.children(node)
        assert rec.op == type(node).__name__
        assert rec.node_id == ir.fingerprint(node)
        assert rec.out_rows == len(_pd_eval(node, pdt)), rec.line
        assert len(rec.children) == len(kids)
        for r, k in zip(rec.children, kids):
            check(r, k)

    check(pr.roots[0], tree)
    assert pr.finished and pr.wall_ms > 0


def test_disabled_mode_is_one_bool_check(monkeypatch):
    """With the gate off, node_enter/op_event/at_node_output must return
    before touching ANY other state — enforced by poisoning every module
    attribute they would consult next."""
    profile.set_enabled(False)

    class Boom:
        def __getattribute__(self, name):
            if name.startswith("__"):          # monkeypatch plumbing
                return object.__getattribute__(self, name)
            raise AssertionError("disabled path touched profiler state")

    monkeypatch.setattr(profile, "_tls", Boom())
    assert profile.node_enter(ir.Scan("t")) is None
    profile.op_event("x", rows=1)          # no-op, no state touched
    profile.annotate_node(engine="dense")
    profile.at_node_output(None)           # never inspects the table
    metrics.profile_op("x", rows=1)        # hook gates before _tls too


def test_disabled_execution_records_nothing(tpcds):
    tables, _ = tpcds
    profile.set_enabled(False)
    profile.reset()
    tree = tpcds_plans.optimized("q55").tree
    lower.execute(tree,
                  lower.TableCatalog(tables, tpcds_plans.TABLE_SCHEMAS),
                  record_stats=False)
    assert profile.completed() == []
    with profile.query("nope") as pr:
        assert pr is None                  # query() is a no-op when off
    assert profile.completed() == []


def test_capture_replay_identical_branches(tpcds, prof_on, monkeypatch):
    """SRJT_PROFILE=1 (+ validity syncs) through compile_query: the
    eager capture and the jitted replay must resolve the same tape —
    including the per-node validity scalars — and return bit-identical
    results.  A nullable column makes the validity sync real."""
    from spark_rapids_jni_tpu.models.compiled import compile_query
    monkeypatch.setenv("SRJT_PROFILE", "1")
    monkeypatch.setenv("SRJT_PROFILE_VALIDITY", "1")
    profile.set_enabled(None)              # re-read both knobs
    assert profile._validity

    rng = np.random.default_rng(7)
    n = 3000
    valid = rng.random(n) > 0.25
    tables = {
        "fact": Table([_col(rng.integers(0, 50, n).astype(np.int64)),
                       _col(rng.integers(1, 9, n).astype(np.int64),
                            validity=valid)]),
        "dim": Table([_col(np.arange(50, dtype=np.int64)),
                      _col((np.arange(50) % 5).astype(np.int32))]),
    }
    schemas = {"fact": ["f_sk", "f_qty"], "dim": ["d_sk", "d_tag"]}
    tree = ir.Sort(ir.Aggregate(
        ir.Join(ir.Scan("fact"), ir.Scan("dim"), ("f_sk",), ("d_sk",)),
        ("d_tag",), (("f_qty", "sum", "total"),)), ("d_tag",))
    qfn = lower.compile_plan(tree, schemas)

    cq = compile_query(qfn, tables)        # capture (validity syncs taped)
    out = cq.run(tables)                   # replay re-trace + dispatch
    _assert_tables_equal(out, cq.expected)
    out2 = cq.run_unchecked(tables)
    _assert_tables_equal(out2, cq.expected)


def test_validity_density_recorded(prof_on, monkeypatch):
    monkeypatch.setenv("SRJT_PROFILE", "1")
    monkeypatch.setenv("SRJT_PROFILE_VALIDITY", "1")
    profile.set_enabled(None)
    n = 1000
    valid = np.zeros(n, bool)
    valid[: n // 4] = True                 # 25% valid
    tables = {"t": Table([_col(np.arange(n, dtype=np.int64)),
                          _col(np.arange(n, dtype=np.int64),
                               validity=valid)])}
    schemas = {"t": ["a", "b"]}
    tree = ir.Filter(ir.Scan("t"), ir.Cmp("<", ir.Col("a"), ir.Lit(n)))
    with profile.query("validity") as pr:
        lower.execute(tree, lower.TableCatalog(tables, schemas),
                      record_stats=False)
    fracs = [r.valid_frac for r in pr.nodes() if r.valid_frac is not None]
    # density counts NULLABLE columns only: col "a" (validity=None) is
    # skipped, col "b" is 25% valid
    assert fracs and all(abs(f - 0.25) < 1e-9 for f in fracs)


def test_mispredict_flag_and_stats_feedback(prof_on):
    n = 2000
    tables = {"t": Table([_col(np.arange(n, dtype=np.int64))])}
    schemas = {"t": ["a"]}
    tree = ir.Filter(ir.Scan("t"), ir.Cmp("<", ir.Col("a"), ir.Lit(10)))
    fp = ir.fingerprint(tree)
    plan_stats.GLOBAL.observe(fp, 2000)    # stale prior: 2000 rows
    with profile.query("mis") as pr:
        lower.execute(tree, lower.TableCatalog(tables, schemas),
                      record_stats=True)
    root = pr.roots[0]
    assert root.est_rows == 2000 and root.out_rows == 10
    assert root.mispredicted()
    assert "mispredict" in json.dumps(root.as_dict())
    # record_stats=True corrected the prior from the observed run
    assert plan_stats.GLOBAL.rows_for(tree) != 2000


def test_explain_analyze_renders(tpcds, prof_on):
    tables, _ = tpcds
    text = profile.explain_analyze(tpcds_plans.PLANS["q55"](),
                                   tpcds_plans.TABLE_SCHEMAS, tables)
    assert "EXPLAIN ANALYZE" in text
    assert "rows est=" in text and "obs=" in text
    assert "time=" in text and "self=" in text
    assert "node(s)" in text


def test_profile_artifact_export(tpcds, prof_on, tmp_path, monkeypatch):
    tables, _ = tpcds
    monkeypatch.setenv("SRJT_PROFILE_DIR", str(tmp_path))
    tree = tpcds_plans.optimized("q55").tree
    with profile.query("q55", ir.fingerprint(tree)):
        lower.execute(tree,
                      lower.TableCatalog(tables,
                                         tpcds_plans.TABLE_SCHEMAS),
                      record_stats=False)
    arts = list(tmp_path.glob("profile-*.json"))
    assert len(arts) == 1
    doc = json.loads(arts[0].read_text())
    assert doc["name"] == "q55" and doc["finished"]
    assert doc["nodes"] and doc["nodes"][0]["out_rows"] is not None


def test_flight_probe_embeds_partial_profile(prof_on):
    n = 100
    tables = {"t": Table([_col(np.arange(n, dtype=np.int64))])}
    schemas = {"t": ["a"]}
    seen = {}

    class Catalog(lower.TableCatalog):
        def scan(self, node):
            # mid-execution: the profile stack has the Scan node open
            seen.update(flight.sample_probes())
            return super().scan(node)

    with profile.query("stuck"):
        lower.execute(ir.Scan("t"), Catalog(tables, schemas),
                      record_stats=False)
    probe = seen.get("plan.active_profile")
    assert probe, seen.keys()
    (prof_dict,) = probe.values()
    assert prof_dict["name"] == "stuck"
    assert prof_dict["open"]               # the in-flight node stack


def test_compile_ledger_attributes_per_fingerprint(tpcds):
    tables, _ = tpcds
    from spark_rapids_jni_tpu.models.compiled import compile_query
    metrics.set_enabled(True)
    metrics.reset()
    try:
        qfn = lower.compile_plan(tpcds_plans.optimized("q55").tree,
                                 tpcds_plans.TABLE_SCHEMAS)
        cq = compile_query(qfn, tables)
        cq.run(tables)
        cq.run(tables)
        led = metrics.ledger_snapshot()
        ent = led[qfn.plan_fingerprint]
        assert ent["captures"] == 1 and ent["capture_ms"] > 0
        assert ent["traces"] >= 1 and ent["trace_ms"] > 0
        assert ent["first_dispatches"] == 1
        assert ent["runs"] == 2
        # visible in the snapshot + prometheus surfaces
        assert qfn.plan_fingerprint in metrics.snapshot()["ledger"]
        prom = metrics.to_prometheus()
        assert "srjt_compile_ledger" in prom
        assert f'plan="{qfn.plan_fingerprint}"' in prom
    finally:
        metrics.set_enabled(None)
        metrics.reset()


def test_chrome_trace_nests_node_spans(tpcds, prof_on, tmp_path):
    tables, _ = tpcds
    metrics.set_enabled(True)
    metrics.reset()
    try:
        tree = tpcds_plans.optimized("q55").tree
        with metrics.query_span("q55"):
            with profile.query("q55"):
                lower.execute(
                    tree, lower.TableCatalog(tables,
                                             tpcds_plans.TABLE_SCHEMAS),
                    record_stats=False)
        doc = metrics.chrome_trace()
        node_evs = [e for e in doc["traceEvents"]
                    if str(e.get("name", "")).startswith("plan.node:")]
        assert node_evs
        assert all("node_id" in (e.get("args") or {}) for e in node_evs)
        roots = [e for e in doc["traceEvents"]
                 if e.get("name") == "query:q55"]
        assert roots
        # node spans sit INSIDE the query span's interval
        r = roots[0]
        for e in node_evs:
            assert e["ts"] >= r["ts"]
            assert e["ts"] + e["dur"] <= r["ts"] + r["dur"] + 1.0
    finally:
        metrics.set_enabled(None)
        metrics.reset()


# --- tool-layer regressions --------------------------------------------------


def test_trace_report_no_nested_double_count(tmp_path):
    """A parent span containing a child must report parent self-time =
    inclusive - child (the flatten-by-name double-count bug)."""
    import tools.trace_report as tr
    events = [
        {"ph": "X", "name": "stage", "ts": 0, "dur": 100_000,
         "pid": 1, "tid": 1},
        {"ph": "X", "name": "join", "ts": 10_000, "dur": 60_000,
         "pid": 1, "tid": 1},
        {"ph": "X", "name": "stage", "ts": 200_000, "dur": 50_000,
         "pid": 1, "tid": 1},
        # same name on another thread: independent lane
        {"ph": "X", "name": "join", "ts": 0, "dur": 30_000,
         "pid": 1, "tid": 2},
    ]
    agg = tr.summarize(events)
    assert agg["stage"]["total_ms"] == 150.0
    assert agg["stage"]["self_ms"] == 90.0       # 100-60 + 50
    assert agg["join"]["self_ms"] == 90.0        # 60 + 30, no parent leak
    p = tmp_path / "t.json"
    p.write_text(json.dumps({"traceEvents": events}))
    assert tr.main(["tr", str(p)]) == 0


def test_trace_report_by_node_mode():
    import tools.trace_report as tr
    events = [
        {"ph": "X", "name": "plan.node:Join", "ts": 0, "dur": 10_000,
         "pid": 1, "tid": 1, "args": {"node_id": "plan:aaa", "line": "J1"}},
        {"ph": "X", "name": "plan.node:Join", "ts": 20_000, "dur": 5_000,
         "pid": 1, "tid": 1, "args": {"node_id": "plan:bbb", "line": "J2"}},
        {"ph": "X", "name": "other", "ts": 0, "dur": 1_000,
         "pid": 1, "tid": 1},
    ]
    agg = tr.summarize(events, by_node=True)
    assert len(agg) == 2                   # grouped by node id, not name
    assert "other" not in " ".join(agg)


def test_profile_report_flatten_and_regress(tmp_path):
    import tools.profile_report as pr
    node = {"op": "Join", "line": "Join x", "node_id": "plan:a",
            "out_rows": 10, "out_bytes": 80, "wall_ms": 10.0,
            "self_ms": 8.0, "children": [
                {"op": "Scan", "line": "Scan t", "node_id": "plan:b",
                 "out_rows": 100, "out_bytes": 800, "wall_ms": 2.0,
                 "self_ms": 2.0}]}
    prof = {"name": "q", "fingerprint": "plan:a", "wall_ms": 10.0,
            "finished": True, "nodes": [node]}
    old = dict(prof)
    new = json.loads(json.dumps(prof))
    new["nodes"][0]["self_ms"] = 80.0      # 10× regression on the join
    (tmp_path / "old").mkdir()
    (tmp_path / "new").mkdir()
    (tmp_path / "old" / "profile-q-1-1.json").write_text(json.dumps(old))
    (tmp_path / "new" / "profile-q-1-1.json").write_text(json.dumps(new))
    agg = pr.flatten([prof])
    assert agg["plan:a"]["self_ms"] == 8.0
    assert agg["plan:b"]["out_rows"] == 100
    regs = pr.regressions(pr.flatten([new]), pr.flatten([old]), 1.5)
    assert len(regs) == 1 and regs[0][0] == "Join x"
    # CI contract: exit 3 on regression, 0 when clean
    assert pr.main(["pr", str(tmp_path / "new"), "--regress",
                    str(tmp_path / "old")]) == 3
    assert pr.main(["pr", str(tmp_path / "old"), "--regress",
                    str(tmp_path / "old")]) == 0


def test_bench_history_flattens_artifacts(tmp_path):
    import tools.bench_history as bh
    (tmp_path / "X_BENCH.json").write_text(json.dumps(
        {"benches": {"a": {"wall_s": 1.5, "ok": True, "name": "a"}},
         "rows": 100}))
    doc = bh.collect(str(tmp_path))
    metrics_ = {m["metric"]: m["value"] for m in doc["metrics"]}
    assert metrics_ == {"benches.a.wall_s": 1.5, "rows": 100.0}
    assert doc["generated_from"] == ["X_BENCH.json"]
    assert bh.main(["bh", "--root", str(tmp_path)]) == 0
    out = json.loads((tmp_path / "BENCH_TRAJECTORY.json").read_text())
    assert out["metrics"][0]["artifact"] == "X_BENCH.json"
